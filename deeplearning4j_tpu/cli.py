"""Command-line entry points.

The reference exposes exactly two ``main()``s (SURVEY.md §1): training via
``ParallelWrapperMain`` (`deeplearning4j-scaleout/.../parallelism/main/ParallelWrapperMain.java`,
JCommander flags: modelPath, workers, averagingFrequency, prefetchSize,
modelOutputPath, uiUrl) and serving via ``NearestNeighborsServer``
(`NearestNeighborsServer.java:3-10`). This module provides both:

- ``python -m deeplearning4j_tpu.cli train ...`` — load a serialized model,
  train it data-parallel over the mesh, save the result.
- ``python -m deeplearning4j_tpu.cli nn-server ...`` — serve k-NN queries
  (delegates to :meth:`NearestNeighborsServer.main`).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

import numpy as np


def parallel_wrapper_main(argv: Optional[List[str]] = None):
    """ParallelWrapperMain parity: train a saved model over the mesh."""
    ap = argparse.ArgumentParser("parallel-wrapper-train")
    ap.add_argument("--modelPath", required=True,
                    help="model zip written by ModelSerializer")
    ap.add_argument("--dataPath", required=True,
                    help=".npz with 'features' and 'labels' arrays")
    ap.add_argument("--modelOutputPath", required=True)
    ap.add_argument("--workers", type=int, default=None,
                    help="mesh data-axis size (default: all devices)")
    ap.add_argument("--mesh", default=None, metavar="AXES",
                    help="2-D GSPMD mesh, e.g. data=4,model=2 (-1 infers "
                         "one axis from the device count): params are "
                         "placed by --sharding-rules over the model "
                         "axis, batches shard over data. With --elastic "
                         "the data size MUST be -1 or absent — the world "
                         "is dynamic (each generation's process count IS "
                         "the data axis); model axes are per-host slices")
    ap.add_argument("--sharding-rules", default=None, dest="sharding_rules",
                    metavar="RULES.json",
                    help="partition rule file (regex over param path -> "
                         "PartitionSpec; lint with "
                         "tools/validate_sharding_rules.py); default: "
                         "the built-in Megatron 2-D rule set")
    ap.add_argument("--mode", choices=("shared_gradients", "averaging"),
                    default="shared_gradients")
    ap.add_argument("--averagingFrequency", type=int, default=5)
    ap.add_argument("--batchSize", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--prefetchSize", type=int, default=2,
                    help="async prefetch buffer (AsyncDataSetIterator)")
    ap.add_argument("--uiUrl", default=None,
                    help="remote UI /remote endpoint to report stats to")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="record the run with the observe tracer and write "
                         "a Chrome trace (chrome://tracing / Perfetto) here")
    ap.add_argument("--log-json", default=None, metavar="OUT.jsonl",
                    dest="log_json",
                    help="structured JSON-lines logging with trace "
                         "correlation to this file ('-' for stderr)")
    ap.add_argument("--watchdog", choices=("off", "log", "raise"),
                    default="off",
                    help="training health watchdog (NaN loss/params, "
                         "gradient explosion, divergence, stalls) with "
                         "this action policy")
    ap.add_argument("--alerts", default=None, metavar="RULES.json",
                    help="evaluate these alert rules against the metrics "
                         "registry in the background during training")
    ap.add_argument("--slo", default=None, metavar="SLO.json",
                    help="load SLO definitions (observe/slo.py schema) and "
                         "evaluate their burn-rate rules alongside --alerts; "
                         "under --elastic the set is surfaced at /slo on "
                         "the --metrics-port server")
    ap.add_argument("--elastic", type=int, default=None, metavar="N",
                    help="run as an elastic multi-process job: N worker "
                         "processes supervised with automatic failure "
                         "recovery and shrink-to-surviving-slice "
                         "(parallel/elastic.py)")
    ap.add_argument("--min-workers", type=int, default=1,
                    dest="min_workers",
                    help="smallest world --elastic may shrink to before "
                         "the job fails loudly")
    ap.add_argument("--ckpt-dir", default=None, dest="ckpt_dir",
                    help="checkpoint/recovery directory (required with "
                         "--elastic): orbax rotation checkpoints, "
                         "generation ledger, heartbeats")
    ap.add_argument("--max-restarts", type=int, default=2,
                    dest="max_restarts",
                    help="per-worker restart budget before the supervisor "
                         "shrinks the world (exponential backoff between "
                         "restarts)")
    ap.add_argument("--heartbeat-timeout", type=float, default=120.0,
                    dest="heartbeat_timeout",
                    help="seconds without a worker heartbeat before the "
                         "supervisor declares it hung and recovers")
    ap.add_argument("--hosts", type=int, default=None,
                    help="group the --elastic workers into this many host "
                         "failure domains: a worker death marks its WHOLE "
                         "host the victim, restart budgets charge the "
                         "host, shrink removes the host (per-host slice "
                         "shapes stay valid). Coordinator bind/advertise "
                         "addresses come from DL4J_TPU_ELASTIC_BIND_HOST/"
                         "DL4J_TPU_ELASTIC_ADVERTISE_HOST (default "
                         "loopback)")
    ap.add_argument("--min-hosts", type=int, default=1, dest="min_hosts",
                    help="smallest number of host groups --elastic may "
                         "shrink to before the job fails loudly")
    ap.add_argument("--save-mode", choices=("sync", "async"),
                    default="sync", dest="save_mode",
                    help="worker checkpoint path: async overlaps orbax "
                         "saves with training (bounded in-flight, "
                         "all-ranks commit protocol); sync blocks the "
                         "step until the save lands")
    ap.add_argument("--progress-timeout", type=float, default=None,
                    dest="progress_timeout",
                    help="arm the partition watchdog: seconds without "
                         "step progress anywhere (while heartbeats stay "
                         "alive) before the supervisor resolves a "
                         "network partition by killing the "
                         "least-progressed side")
    ap.add_argument("--metrics-port", type=int, default=None,
                    dest="metrics_port",
                    help="with --elastic: serve the job-wide metrics "
                         "union (workers re-labeled {slot,host,"
                         "generation} + supervisor series) at this "
                         "port's /metrics (0 = ephemeral)")
    args = ap.parse_args(argv)

    mesh_axes = None
    if args.mesh:
        from deeplearning4j_tpu.parallel.mesh import parse_mesh_axes
        try:
            mesh_axes = parse_mesh_axes(args.mesh)
        except ValueError as e:
            ap.error(f"--mesh: {e}")
        if args.workers is not None:
            ap.error("--workers and --mesh both size the data axis; "
                     "use --mesh data=N[,model=M] alone")
    if args.sharding_rules and not args.mesh:
        ap.error("--sharding-rules needs --mesh (the rules place params "
                 "over the mesh's model axes)")
    if args.sharding_rules:
        # an unreadable/invalid rule file fails BEFORE training (and
        # before worker processes are launched under --elastic)
        from deeplearning4j_tpu.parallel.sharding import load_sharding_rules
        try:
            load_sharding_rules(args.sharding_rules)
        except (OSError, ValueError) as e:
            ap.error(f"--sharding-rules: {e}")

    if args.elastic is not None:
        if not args.ckpt_dir:
            ap.error("--elastic requires --ckpt-dir (the recovery "
                     "substrate: rotation checkpoints + generation ledger)")
        # flags that act INSIDE the training process are not plumbed into
        # the supervised workers — reject rather than silently ignore
        # (--trace IS supported: workers stream spans back and the
        # supervisor writes ONE merged fleet trace)
        unsupported = [flag for flag, hit in (
            ("--workers", args.workers is not None),
            ("--mode averaging", args.mode != "shared_gradients"),
            ("--averagingFrequency", args.averagingFrequency != 5),
            ("--prefetchSize", args.prefetchSize != 2),
            ("--uiUrl", args.uiUrl is not None),
            ("--watchdog", args.watchdog != "off"),
        ) if hit]
        if unsupported:
            ap.error(
                f"{', '.join(unsupported)} affect(s) in-process training "
                "and is not forwarded to --elastic workers (they train "
                "shared_gradients at the elastic world size); drop it, or "
                "run without --elastic. --log-json, --alerts, --slo, "
                "--trace and --metrics-port ARE supported (they observe "
                "the fleet)")
        if mesh_axes is not None and mesh_axes.get("data", -1) != -1:
            # the elastic world is dynamic: each generation's process
            # count IS the data extent, so a pinned size is a lie the
            # first shrink would expose
            ap.error(f"--mesh data={mesh_axes['data']} cannot be pinned "
                     "under --elastic (the supervisor sizes the data axis "
                     "to the live world); use data=-1 or omit it, e.g. "
                     "--mesh model=2")
        return _elastic_train(args, mesh_axes=mesh_axes)
    if args.metrics_port is not None:
        ap.error("--metrics-port only applies to --elastic jobs (the "
                 "in-process serve command exposes /metrics itself)")

    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util import model_serializer

    net = model_serializer.restore_model(args.modelPath)
    z = np.load(args.dataPath)
    ds = DataSet(z["features"], z["labels"])
    it = ListDataSetIterator(ds, args.batchSize, shuffle=True)
    if args.uiUrl:
        from deeplearning4j_tpu.ui import StatsListener
        from deeplearning4j_tpu.ui.remote import RemoteUIStatsStorageRouter
        net.listeners.append(
            StatsListener(RemoteUIStatsStorageRouter(args.uiUrl)))
    tracer = None
    if args.log_json:
        from deeplearning4j_tpu.observe import enable_structured_logging
        if args.log_json == "-":
            enable_structured_logging(stream=sys.stderr)
        else:
            enable_structured_logging(path=args.log_json)
    if args.trace:
        from deeplearning4j_tpu.observe import default_registry, enable_tracing
        tracer = enable_tracing(metrics=default_registry())
    if args.trace or args.watchdog != "off" or args.alerts or args.slo:
        # one attachment path for TraceListener AND the watchdog. With
        # --alerts/--slo the TraceListener is attached even without
        # --trace: it is what exports the training_* series into the
        # registry the rules evaluate (spans stay off while tracing is
        # not enabled)
        from deeplearning4j_tpu.observe import (attach_observability,
                                                default_registry)
        attach_observability(
            net, tracer=tracer, metrics=default_registry(),
            trace=bool(args.trace) or bool(args.alerts) or bool(args.slo),
            watchdog=(None if args.watchdog == "off"
                      else {"action": args.watchdog}))
    alert_mgr = None
    if args.alerts or args.slo:
        from deeplearning4j_tpu.observe import (AlertManager, LogSink,
                                                default_registry, load_rules,
                                                load_slos)
        rules = list(load_rules(args.alerts)) if args.alerts else []
        if args.slo:
            rules += load_slos(args.slo).rules()
        alert_mgr = AlertManager(default_registry(),
                                 rules, [LogSink()],
                                 interval_s=5.0).start()
    mesh = None
    gspmd = mesh_axes is not None and any(
        k != "data" and int(v) > 1 for k, v in mesh_axes.items())
    if gspmd:
        # DP×MP: the jitted train step IS the distributed program — the
        # replica-averaging knobs have nothing to act on
        unsupported = [flag for flag, hit in (
            ("--mode averaging", args.mode != "shared_gradients"),
            ("--averagingFrequency", args.averagingFrequency != 5),
        ) if hit]
        if unsupported:
            ap.error(f"{', '.join(unsupported)} drive(s) the replica-"
                     "averaging ParallelWrapper and do(es) not apply to a "
                     "--mesh with model axes (GSPMD shards ONE program)")
        from deeplearning4j_tpu.parallel.sharding import (
            load_sharding_rules, shard_model_with_rules)
        mesh = make_mesh(mesh_axes)
        rules = (load_sharding_rules(args.sharding_rules)
                 if args.sharding_rules else None)
        shard_model_with_rules(net, mesh, rules)
        print(f"GSPMD mesh {args.mesh}: params placed by "
              f"{args.sharding_rules or 'the default 2-D rule set'}")
        pw = None
    else:
        if mesh_axes is not None:  # data-only --mesh ≡ --workers
            mesh = make_mesh(mesh_axes)
        elif args.workers:
            mesh = make_mesh({"data": args.workers})
        pw = ParallelWrapper(net, mesh, mode=args.mode,
                             averaging_frequency=args.averagingFrequency,
                             metrics=(None if tracer is None
                                      else tracer.metrics))
    try:
        if pw is not None:
            pw.fit(it, epochs=args.epochs, prefetch_depth=args.prefetchSize)
        else:
            net.fit(it, epochs=args.epochs)
    finally:
        if alert_mgr is not None:
            alert_mgr.evaluate_once()  # final round so late series count
            alert_mgr.stop()
            firing = alert_mgr.firing()
            print(f"alerts firing at exit: {firing if firing else 'none'}")
        if tracer is not None:
            from deeplearning4j_tpu.observe import disable_tracing
            n = tracer.flush(args.trace)
            print(f"wrote Chrome trace ({n} spans) to {args.trace}")
            print(tracer.timeline(limit=40))
            disable_tracing()
        if args.log_json:
            from deeplearning4j_tpu.observe import disable_structured_logging
            disable_structured_logging()
    model_serializer.write_model(net, args.modelOutputPath)
    return net


def _elastic_train(args, mesh_axes=None):
    """``train --elastic N``: supervise N elastic worker processes
    (``python -m deeplearning4j_tpu.parallel.elastic_worker``) over the
    model/data from --modelPath/--dataPath. Worker death triggers
    automatic recovery — restart-in-place under a backoff budget, then
    shrink to the surviving slice down to --min-workers. Rank 0 of the
    finishing generation writes --modelOutputPath. ``--log-json``
    observes the supervisor; ``--alerts`` evaluates against the FLEET
    union (worker ``training_*`` series re-labeled
    ``{slot,host,generation}`` plus the supervisor's ``elastic_*``
    series — a FleetRegistry is created for the rules even without
    ``--metrics-port``); ``--trace`` writes ONE merged fleet timeline."""
    from deeplearning4j_tpu.parallel.elastic import (BackoffPolicy,
                                                     ElasticJobSupervisor,
                                                     WorkerSpec)

    if args.log_json:
        from deeplearning4j_tpu.observe import enable_structured_logging
        if args.log_json == "-":
            enable_structured_logging(stream=sys.stderr)
        else:
            enable_structured_logging(path=args.log_json)
    tracer = None
    if args.trace:
        # fleet tracing: the supervisor's generation/decision spans land
        # in its own ring; workers stream theirs back through the
        # ckpt-dir trace files; ONE merged timeline is written at exit
        from deeplearning4j_tpu.observe import default_registry, enable_tracing
        tracer = enable_tracing(metrics=default_registry())

    worker_mesh = None
    if mesh_axes:
        # the data axis is the live world size, owned by the supervisor;
        # only the per-host model axes ride the WorkerSpec
        worker_mesh = {k: v for k, v in mesh_axes.items() if k != "data"}
    spec = WorkerSpec(argv=[
        sys.executable, "-m", "deeplearning4j_tpu.parallel.elastic_worker",
        "--modelPath", args.modelPath,
        "--dataPath", args.dataPath,
        "--out", args.modelOutputPath,
        "--batchSize", str(args.batchSize),
        "--epochs", str(args.epochs),
        "--save-mode", args.save_mode,
    ], mesh_axes=worker_mesh or None,
        sharding_rules=args.sharding_rules)
    fleet = None
    if (args.alerts or args.slo) and args.metrics_port is None:
        # --alerts/--slo observe the FLEET: the rules must see the
        # job-wide union ({slot,host,generation}-labeled worker series),
        # so a FleetRegistry exists whenever rules do, scrape port or not
        from deeplearning4j_tpu.observe import FleetRegistry, default_registry
        fleet = FleetRegistry(local=default_registry())
    supervisor = ElasticJobSupervisor(
        spec, num_workers=args.elastic, min_workers=args.min_workers,
        num_hosts=args.hosts, min_hosts=args.min_hosts,
        ckpt_dir=args.ckpt_dir,
        backoff=BackoffPolicy(max_restarts=args.max_restarts),
        heartbeat_timeout_s=args.heartbeat_timeout,
        progress_timeout_s=args.progress_timeout,
        metrics_port=args.metrics_port, fleet=fleet)
    alert_mgr = None
    if args.alerts or args.slo:
        from deeplearning4j_tpu.observe import (AlertManager, LogSink,
                                                load_rules, load_slos)
        rules = list(load_rules(args.alerts)) if args.alerts else []
        if args.slo:
            slo_set = load_slos(args.slo)
            rules += slo_set.rules()
            supervisor.slo = slo_set  # surfaced at /slo on the
            # --metrics-port server
        alert_mgr = AlertManager(
            supervisor.fleet, rules, [LogSink()],
            interval_s=5.0).start()
        supervisor.alerts = alert_mgr  # surfaced at /alerts on the
        # --metrics-port server
    try:
        result = supervisor.run()
    finally:
        if alert_mgr is not None:
            alert_mgr.evaluate_once()
            alert_mgr.stop()
            firing = alert_mgr.firing()
            print(f"alerts firing at exit: {firing if firing else 'none'}")
        if tracer is not None:
            from deeplearning4j_tpu.observe import disable_tracing
            n = supervisor.write_fleet_trace(args.trace)
            print(f"wrote merged fleet trace ({n} events) to {args.trace}")
            disable_tracing()
        if args.log_json:
            from deeplearning4j_tpu.observe import (
                disable_structured_logging)
            disable_structured_logging()
    last = result.generations[-1]
    print(f"elastic job {result.status}: {len(result.generations)} "
          f"generation(s), {result.restarts_total} recovery event(s), "
          f"final world {last.world} "
          f"(min_workers={args.min_workers})")
    print(f"wrote {args.modelOutputPath}")
    return result


def cluster_setup_main(argv: Optional[List[str]] = None, runner=None):
    """``ClusterSetup`` parity (``aws/ec2/provision/ClusterSetup.java``
    JCommander flags → argparse): bring up N TPU VMs, wait until READY,
    provision each with the worker script. ``runner`` is injectable for
    tests/dry runs; ``--dry-run`` prints the gcloud commands instead."""
    ap = argparse.ArgumentParser("cloud-setup")
    ap.add_argument("-w", "--workers", type=int, default=1,
                    help="number of TPU VMs (ClusterSetup -w)")
    ap.add_argument("--project", required=True)
    ap.add_argument("--zone", required=True,
                    help="GCP zone (the -region flag's role)")
    ap.add_argument("--accelerator-type", default="v5p-8",
                    help="TPU slice type (the -s instance-size flag's role)")
    ap.add_argument("--version", default="tpu-ubuntu2204-base",
                    help="TPU VM image (the -ami flag's role)")
    ap.add_argument("--name-prefix", default="dl4j-tpu")
    ap.add_argument("--wscript", default=None,
                    help="worker setup script to upload and run on every VM")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the gcloud commands; execute nothing")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.cloud import ClusterProvisioner, TpuProvisioner

    if args.dry_run:
        import shlex
        runner = lambda cmd: (print(shlex.join(cmd)), "READY")[-1]
    prov = TpuProvisioner(args.project, args.zone, runner=runner)
    cluster = ClusterProvisioner(prov, num_workers=args.workers,
                                 accelerator_type=args.accelerator_type,
                                 version=args.version,
                                 name_prefix=args.name_prefix)
    cluster.create()
    cluster.block_till_all_running(poll_seconds=0.0 if args.dry_run else 10.0)
    if args.wscript:
        cluster.provision_workers(args.wscript)
    return cluster


def _lower_step_hlo(net, ds) -> str:
    """Compiled HLO text of the net's jitted train step (MLN or graph)."""
    import jax.numpy as jnp
    dtype = net.conf.global_conf.jnp_dtype()
    it = jnp.asarray(net.iteration, jnp.float32)
    ep = jnp.asarray(net.epoch, jnp.float32)
    rng = net._next_rng()
    if hasattr(net, "_to_mds"):  # ComputationGraph
        mds = net._to_mds(ds)
        inputs = {n: jnp.asarray(f, dtype)
                  for n, f in zip(net.conf.inputs, mds.features)}
        labels = [jnp.asarray(l, dtype) for l in mds.labels]
        step = net._get_train_step()
        lowered = step.lower(net.params, net.states, net.updater_states,
                             it, ep, inputs, labels, None, None, rng)
    else:  # MultiLayerNetwork
        x = jnp.asarray(np.asarray(ds.features), dtype)
        y = jnp.asarray(np.asarray(ds.labels), dtype)
        step = net._get_train_step(False)
        lowered = step.lower(net.params, net.states, net.updater_states,
                             it, ep, x, y, None, None, rng, None)
    return lowered.compile().as_text()


def profile_main(argv: Optional[List[str]] = None):
    """Profile a saved model's jitted train step on the current backend:
    a trace window via ProfilerListener, bucketed per-op device time via
    the HLO-mapped xplane analysis (the tools/tpu_perf_session.py
    machinery, exposed as a framework command)."""
    import json as _json
    import os as _os

    ap = argparse.ArgumentParser("profile")
    ap.add_argument("--modelPath", required=True,
                    help="model zip written by ModelSerializer")
    ap.add_argument("--dataPath", required=True,
                    help=".npz with 'features' and 'labels' arrays")
    ap.add_argument("--batchSize", type=int, default=32)
    ap.add_argument("--logDir", default="/tmp/dl4j_tpu_profile")
    ap.add_argument("--out", default=None, help="write the report JSON here")
    args = ap.parse_args(argv)

    _os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                           "python")
    tools = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    from hlo_map import HloModule
    from tpu_perf_session import profile_step

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.util import model_serializer

    net = model_serializer.restore_model(args.modelPath)
    z = np.load(args.dataPath)
    ds = DataSet(z["features"][:args.batchSize],
                 z["labels"][:args.batchSize])
    mod = HloModule(_lower_step_hlo(net, ds))
    times = profile_step(net, ds, args.logDir)
    total = sum(t for t, _ in times.values())
    buckets = {}
    batch = int(np.asarray(ds.features).shape[0])
    for nm, (t, c) in times.items():
        key = nm.split(" = ")[0].strip().lstrip("%")
        cat, flops = mod.classify(key, batch)
        b = buckets.setdefault(cat, {"time": 0.0, "flops": 0})
        b["time"] += t
        b["flops"] += flops * c
    report = {
        "device_ms_per_step": round(total / 4 * 1e3, 3),
        "buckets": {k: {"share_pct": round(v["time"] / total * 100, 1),
                        "ms_per_step": round(v["time"] / 4 * 1e3, 3),
                        "tflops": (round(v["flops"] / v["time"] / 1e12, 1)
                                   if v["flops"] else None)}
                    for k, v in sorted(buckets.items(),
                                       key=lambda kv: -kv[1]["time"])},
    }
    print(_json.dumps(report, indent=1))
    if args.out:
        with open(args.out, "w") as fh:
            _json.dump(report, fh, indent=1)


def evaluate_main(argv: Optional[List[str]] = None):
    """``evaluate`` subcommand: load any supported model artifact
    (ModelGuesser chain) and print classification metrics over a CSV
    dataset — the ``MultiLayerNetwork.evaluate`` flow from the shell."""
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu evaluate")
    p.add_argument("--model", required=True,
                   help="model artifact (own/DL4J zip or Keras h5)")
    p.add_argument("--csv", required=True, help="delimited dataset file")
    p.add_argument("--label-index", type=int, default=-1,
                   help="label column (default: last column)")
    p.add_argument("--classes", type=int, required=True,
                   help="number of classes")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--delimiter", default=",")
    p.add_argument("--skip-lines", type=int, default=0)
    p.add_argument("--top-n", type=int, default=1)
    args = p.parse_args(argv)

    from deeplearning4j_tpu.datasets.records import (
        CSVRecordReader,
        RecordReaderDataSetIterator,
    )
    from deeplearning4j_tpu.util.model_guesser import load_model_guess

    model = load_model_guess(args.model)
    reader = CSVRecordReader(args.csv, skip_lines=args.skip_lines,
                             delimiter=args.delimiter)
    label_index = args.label_index
    if label_index < 0:
        first = reader.next_record()
        reader.reset()
        label_index = len(first) - 1  # a Record is a list of values
    it = RecordReaderDataSetIterator(reader, args.batch,
                                     label_index=label_index,
                                     num_possible_labels=args.classes)
    e = model.evaluate(it, top_n=args.top_n)
    print(e.stats())
    return e


def serve_main(argv: Optional[List[str]] = None, block: bool = True):
    """``serve`` subcommand: stand up the production serving tier from the
    shell — register one or more model artifacts (ModelGuesser chain:
    own/DL4J zips, Keras h5) under names and serve them over HTTP with
    admission control and ``/metrics``."""
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu serve")
    p.add_argument("--model", action="append", required=True,
                   metavar="NAME=PATH",
                   help="model to register (repeatable); NAME=PATH, or a "
                        "bare PATH served under its file stem")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8500,
                   help="listen port (0 → ephemeral)")
    p.add_argument("--max-batch-size", type=int, default=32)
    p.add_argument("--wait-ms", type=float, default=2.0,
                   help="batching window measured from the oldest request")
    p.add_argument("--mesh", default=None, metavar="AXES",
                   help="serve every --model GSPMD-sharded over this 2-D "
                        "mesh, e.g. data=4,model=2 (-1 infers one axis): "
                        "params placed by --sharding-rules, request "
                        "batches sharded over data, buckets rounded to "
                        "the data-axis size")
    p.add_argument("--sharding-rules", default=None, dest="sharding_rules",
                   metavar="RULES.json",
                   help="partition rule file for --mesh (default: the "
                        "built-in Megatron 2-D rule set); lint with "
                        "tools/validate_sharding_rules.py")
    p.add_argument("--buckets", default=None, metavar="N,N,...",
                   help="declared batch buckets (default: powers of two up "
                        "to --max-batch-size); these are pre-compiled at "
                        "registration and the dispatcher pads to them")
    p.add_argument("--warmup", choices=("sync", "async", "off"),
                   default="sync",
                   help="AOT bucket warmup at registration: sync blocks "
                        "until every bucket is compiled, async warms in "
                        "the background (/readyz lists cold buckets), off "
                        "restores lazy first-request compilation")
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="persistent XLA compilation cache: restarts and "
                        "rollbacks re-warm from disk instead of compiling")
    p.add_argument("--dtype-policy", action="append", default=[],
                   metavar="NAME=POLICY",
                   help="serve NAME quantized: POLICY is int8, bf16 or "
                        "float32 (repeatable)")
    p.add_argument("--input-shape", action="append", default=[],
                   metavar="NAME=DIMS",
                   help="per-row input shape for warmup when the model "
                        "conf does not declare one, e.g. lenet=28x28x1 "
                        "(repeatable)")
    p.add_argument("--max-inflight", type=int, default=64,
                   help="admission limit before requests shed as 429")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline (504 past expiry)")
    p.add_argument("--max-dispatcher-restarts", type=int, default=2,
                   help="in-place restarts of a crashed batching "
                        "dispatcher before the crash is terminal "
                        "(exponential backoff between restarts; 0 "
                        "restores the old die-forever behavior)")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="forward crashes within --breaker-window that "
                        "quarantine a model version (per-version circuit "
                        "breaker; 0 disables breakers)")
    p.add_argument("--breaker-window", type=float, default=30.0,
                   help="rolling window (seconds) the crash threshold "
                        "counts over")
    p.add_argument("--breaker-cooldown", type=float, default=5.0,
                   help="seconds an open breaker waits before letting a "
                        "half-open probe through")
    p.add_argument("--breaker-probes", type=int, default=1,
                   help="consecutive probe successes that close a "
                        "half-open breaker")
    p.add_argument("--fallback", action="append", default=[],
                   metavar="NAME=VERSION",
                   help="failover chain for NAME while its live version "
                        "is quarantined/crashed: a version number, "
                        "'previous', or a comma list (repeatable)")
    p.add_argument("--brownout", action="store_true",
                   help="enable brownout degradation: under sustained "
                        "admission saturation, shed X-Priority<=0 "
                        "traffic with 429 and route un-pinned predicts "
                        "to the --fallback chain until pressure clears")
    p.add_argument("--brownout-saturation", type=float, default=0.9,
                   help="fraction of --max-inflight that counts as "
                        "saturation pressure")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="trace requests (spans across HTTP, dispatcher and "
                        "device) and write a Chrome trace here on shutdown")
    p.add_argument("--log-json", default=None, metavar="OUT.jsonl",
                   dest="log_json",
                   help="structured JSON-lines logging with trace "
                        "correlation to this file ('-' for stderr)")
    p.add_argument("--alerts", default=None, metavar="RULES.json",
                   help="alert rules evaluated against /metrics in the "
                        "background; state served at /alerts")
    p.add_argument("--alert-interval", type=float, default=15.0,
                   help="seconds between alert evaluation rounds")
    p.add_argument("--slo", default=None, metavar="SLO.json",
                   help="load SLO definitions (observe/slo.py schema): "
                        "their burn-rate rules join --alerts evaluation "
                        "and compliance is served at /slo")
    args = p.parse_args(argv)

    import os

    from deeplearning4j_tpu.serving import (DTYPE_POLICIES,
                                            ModelRegistry, ModelServer,
                                            default_registry)

    tracer = None
    if args.trace:
        from deeplearning4j_tpu.observe import enable_tracing
        tracer = enable_tracing(metrics=default_registry())
    if args.log_json:
        from deeplearning4j_tpu.observe import enable_structured_logging
        if args.log_json == "-":
            enable_structured_logging(stream=sys.stderr)
        else:
            enable_structured_logging(path=args.log_json)
    slo_set = None
    if args.slo:
        from deeplearning4j_tpu.observe import load_slos
        try:
            slo_set = load_slos(args.slo)
        except (ValueError, OSError) as e:
            p.error(f"--slo: {e}")
        print(f"serving {len(slo_set.slos)} SLO(s) from {args.slo} "
              "(compliance at /slo)")
    alert_mgr = None
    if args.alerts or slo_set is not None:
        from deeplearning4j_tpu.observe import (AlertManager, LogSink,
                                                load_rules)
        rules = list(load_rules(args.alerts)) if args.alerts else []
        if slo_set is not None:
            rules += slo_set.rules()
        alert_mgr = AlertManager(default_registry(),
                                 rules, [LogSink()],
                                 interval_s=args.alert_interval).start()
        print(f"alerting on {len(alert_mgr.rules)} rule(s) from "
              f"{args.alerts or args.slo} (state at /alerts)")

    serve_mesh = None
    serve_rules = None
    if args.sharding_rules and not args.mesh:
        p.error("--sharding-rules needs --mesh (the rules place params "
                "over the mesh's model axes)")
    if args.mesh:
        from deeplearning4j_tpu.parallel.mesh import (make_mesh,
                                                      parse_mesh_axes)
        try:
            serve_mesh = make_mesh(parse_mesh_axes(args.mesh))
        except ValueError as e:
            p.error(f"--mesh: {e}")
        if args.sharding_rules:
            from deeplearning4j_tpu.parallel.sharding import (
                load_sharding_rules)
            try:
                serve_rules = load_sharding_rules(args.sharding_rules)
            except (OSError, ValueError) as e:
                p.error(f"--sharding-rules: {e}")
        if args.dtype_policy:
            p.error("--dtype-policy cannot combine with --mesh (GSPMD-"
                    "sharded serving is float32-only)")

    buckets = None
    if args.buckets:
        try:
            buckets = [int(b) for b in args.buckets.split(",") if b.strip()]
        except ValueError:
            p.error(f"--buckets needs comma-separated batch sizes, "
                    f"got {args.buckets!r}")
        if not buckets or min(buckets) < 1:
            p.error(f"--buckets needs positive batch sizes, "
                    f"got {args.buckets!r}")
    policies = {}
    for spec in args.dtype_policy:
        name, sep, policy = spec.partition("=")
        if not sep:
            p.error(f"--dtype-policy needs NAME=POLICY, got {spec!r}")
        if policy not in DTYPE_POLICIES:
            p.error(f"--dtype-policy {name}={policy!r}: unknown policy "
                    f"(one of {', '.join(DTYPE_POLICIES)})")
        policies[name] = policy
    shapes = {}
    for spec in args.input_shape:
        name, sep, dims = spec.partition("=")
        if not sep:
            p.error(f"--input-shape needs NAME=DIMS, got {spec!r}")
        try:
            shapes[name] = tuple(int(d) for d in dims.lower().split("x"))
        except ValueError:
            p.error(f"--input-shape needs DIMS like 28x28x1, got {dims!r}")
        if not shapes[name] or min(shapes[name]) < 1:
            p.error(f"--input-shape needs positive DIMS, got {dims!r}")
    fallbacks = {}
    for spec in args.fallback:
        name, sep, chain = spec.partition("=")
        if not sep or not chain:
            p.error(f"--fallback needs NAME=VERSION, got {spec!r}")
        parsed_chain = []
        for entry in chain.split(","):
            entry = entry.strip()
            if entry == "previous":
                parsed_chain.append("previous")
                continue
            try:
                parsed_chain.append(int(entry))
            except ValueError:
                p.error(f"--fallback {spec!r}: entries are version "
                        f"numbers or 'previous', got {entry!r}")
        fallbacks[name] = parsed_chain
    if args.max_dispatcher_restarts < 0:
        p.error("--max-dispatcher-restarts must be >= 0")
    if args.breaker_threshold < 0:
        p.error("--breaker-threshold must be >= 0 (0 disables)")
    breaker = None
    if args.breaker_threshold > 0:
        breaker = dict(failure_threshold=args.breaker_threshold,
                       window_s=args.breaker_window,
                       cooldown_s=args.breaker_cooldown,
                       half_open_probes=args.breaker_probes)
    registry = ModelRegistry(metrics=default_registry(),
                             max_batch_size=args.max_batch_size,
                             wait_ms=args.wait_ms, buckets=buckets,
                             warmup=args.warmup,
                             compile_cache_dir=args.compile_cache_dir,
                             max_dispatcher_restarts=(
                                 args.max_dispatcher_restarts),
                             breaker=breaker)
    models = []
    for spec in args.model:
        name, sep, path = spec.partition("=")
        if not sep:
            name, path = os.path.splitext(os.path.basename(spec))[0], spec
        models.append((name, path))
    model_names = [n for n, _ in models]
    # a typo'd NAME in a per-model flag must not silently serve the model
    # unquantized / unwarmed
    for flag, mapping in (("--dtype-policy", policies),
                          ("--input-shape", shapes),
                          ("--fallback", fallbacks)):
        unknown = sorted(set(mapping) - set(model_names))
        if unknown:
            p.error(f"{flag} names no registered --model: "
                    f"{', '.join(unknown)} (models: "
                    f"{', '.join(model_names)})")
    for name, path in models:
        version = registry.register(
            name, path=path, dtype_policy=policies.get(name, "float32"),
            input_shape=shapes.get(name),
            mesh=serve_mesh, sharding_rules=serve_rules)
        state = registry.warmup_state(name, version)
        mesh_tag = "" if serve_mesh is None else f" [mesh {args.mesh}]"
        extra = ""
        if state["status"] == "warm":
            extra = (f" (warmed {len(state['warm'])} bucket(s) in "
                     f"{state['seconds']:.2f}s)")
        elif state["status"] in ("pending", "warming"):
            extra = " (warming in background)"
        elif state["status"] == "skipped":
            extra = f" (warmup skipped: {state['reason']})"
        elif state["status"] == "error":
            extra = f" (warmup FAILED: {state['reason']})"
        print(f"registered {name!r} v{version} from {path}{mesh_tag}{extra}")
    for name, chain in fallbacks.items():
        try:
            registry.set_fallback(name, chain)
        except (KeyError, ValueError) as e:
            p.error(f"--fallback {name}: {e}")
        print(f"fallback chain for {name!r}: {chain}")
    brownout = None
    if args.brownout:
        brownout = dict(saturation=args.brownout_saturation)
    server = ModelServer(
        registry, host=args.host, port=args.port, metrics=default_registry(),
        max_inflight=args.max_inflight,
        default_deadline_s=(args.deadline_ms / 1e3
                            if args.deadline_ms is not None else None),
        alerts=alert_mgr, brownout=brownout, slo=slo_set)
    port = server.start()
    print(f"model server listening on {server.url} "
          f"(models: {', '.join(registry.names())}); port {port}")
    if tracer is not None:
        # the trace flushes when the server stops, however it is stopped —
        # the blocking KeyboardInterrupt path AND block=False callers
        server.tracer = tracer
        orig_stop = server.stop

        def _stop_and_flush(*a, **kw):
            from deeplearning4j_tpu.observe import disable_tracing
            try:
                return orig_stop(*a, **kw)
            finally:
                n = tracer.flush(args.trace)
                print(f"wrote Chrome trace ({n} spans) to {args.trace}")
                disable_tracing()

        server.stop = _stop_and_flush
    if block:
        try:
            server._thread.join()
        except KeyboardInterrupt:
            server.stop(drain=True, shutdown_registry=True)
    return server


def pipeline_main(argv: Optional[List[str]] = None):
    """``pipeline`` subcommand: the continuous-training loop
    (``deeplearning4j_tpu/pipeline/``) as a self-contained product —
    register the saved model as the serving baseline, stream the dataset
    through mini-epoch retraining, gate the candidate on a held-out
    split, canary it at ramped traffic fractions (self-driven synthetic
    traffic from the eval split; a production deployment attaches a
    ModelServer and real traffic), and auto-promote or roll back.  The
    journal under ``--state-dir`` makes the run crash-safe: re-running
    the same command after a kill resumes at the crashed stage
    (``DL4J_TPU_FAULT_PLAN`` with worker ``"pipeline"`` injects such
    kills deterministically).  SIGTERM drains cleanly: the open run is
    decided as a journaled rollback instead of dying mid-stage."""
    import signal

    p = argparse.ArgumentParser(prog="deeplearning4j_tpu pipeline")
    p.add_argument("--modelPath", required=True,
                   help="serving baseline (model zip / DL4J / Keras h5)")
    p.add_argument("--dataPath", required=True,
                   help=".npz with 'features' and 'labels': the stream "
                        "source and (split off) the held-out eval set")
    p.add_argument("--config", required=True, metavar="PIPELINE.json",
                   help="pipeline config (schema: pipeline.PipelineConfig; "
                        "lint with tools/validate_pipeline_config.py)")
    p.add_argument("--state-dir", required=True, dest="state_dir",
                   help="journal + candidate-checkpoint directory (the "
                        "crash-recovery substrate; reuse it to resume)")
    p.add_argument("--eval-fraction", type=float, default=0.2,
                   dest="eval_fraction",
                   help="tail fraction of the dataset held out for the "
                        "eval gate (never streamed)")
    p.add_argument("--cycles", type=int, default=None,
                   help="pipeline runs to execute (default: config)")
    p.add_argument("--modelOutputPath", default=None,
                   help="write the final serving model here on exit")
    p.add_argument("--log-json", default=None, metavar="OUT.jsonl",
                   dest="log_json",
                   help="structured JSON-lines logging with trace "
                        "correlation to this file ('-' for stderr)")
    p.add_argument("--alerts", default=None, metavar="RULES.json",
                   help="alert rules evaluated against the pipeline's "
                        "metrics registry; firing rules roll a canary "
                        "back (config canary.abort_on_alerts)")
    p.add_argument("--alert-interval", type=float, default=5.0,
                   help="seconds between alert evaluation rounds")
    # in-process-only flags are rejected, not silently ignored — the
    # same contract train --elastic applies to its worker processes
    p.add_argument("--trace", default=None, help=argparse.SUPPRESS)
    p.add_argument("--watchdog", default=None, help=argparse.SUPPRESS)
    p.add_argument("--uiUrl", default=None, help=argparse.SUPPRESS)
    p.add_argument("--workers", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    unsupported = [flag for flag, hit in (
        ("--trace", args.trace is not None),
        ("--watchdog", args.watchdog is not None),
        ("--uiUrl", args.uiUrl is not None),
        ("--workers", args.workers is not None),
    ) if hit]
    if unsupported:
        p.error(f"{', '.join(unsupported)} affect(s) in-process training "
                "and is not a pipeline flag: the watchdog is configured "
                "in the pipeline config (train.watchdog) and tracing/UI "
                "belong to the train subcommand. --log-json and --alerts "
                "ARE supported (they observe the pipeline)")
    if not 0.0 < args.eval_fraction < 1.0:
        p.error(f"--eval-fraction must be in (0, 1), "
                f"got {args.eval_fraction}")

    import time

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.observe.metrics import default_registry
    from deeplearning4j_tpu.pipeline import (ContinuousPipeline,
                                             PipelineConfig, StreamBuffer)
    from deeplearning4j_tpu.serving import ModelRegistry
    from deeplearning4j_tpu.streaming import Route
    from deeplearning4j_tpu.util import model_serializer

    config = PipelineConfig.parse(args.config)
    if args.log_json:
        from deeplearning4j_tpu.observe import enable_structured_logging
        if args.log_json == "-":
            enable_structured_logging(stream=sys.stderr)
        else:
            enable_structured_logging(path=args.log_json)
    metrics = default_registry()
    alert_mgr = None
    if args.alerts:
        from deeplearning4j_tpu.observe import (AlertManager, LogSink,
                                                load_rules)
        alert_mgr = AlertManager(metrics, load_rules(args.alerts),
                                 [LogSink()],
                                 interval_s=args.alert_interval).start()

    z = np.load(args.dataPath)
    features = np.asarray(z["features"], np.float32)
    labels = np.asarray(z["labels"], np.float32)
    n_eval = max(1, int(len(features) * args.eval_fraction))
    eval_set = DataSet(features[-n_eval:], labels[-n_eval:])
    stream_x, stream_y = features[:-n_eval], labels[:-n_eval]

    registry = ModelRegistry(metrics=metrics, wait_ms=1.0)
    registry.register(config.name, path=args.modelPath,
                      sample_input=features[:1])

    bs = config.train["batch_size"]
    batches = [DataSet(stream_x[i:i + bs], stream_y[i:i + bs])
               for i in range(0, len(stream_x), bs)]
    cycles = args.cycles if args.cycles is not None else config.cycles
    # hold every cycle's pass outright (buffer stores references to the
    # already-materialized batch list): a cycle that drains less than a
    # full pass must not leave a later cycle's route blocked in put()
    buffer = StreamBuffer(
        capacity=max(1024, (cycles + 1) * max(1, len(batches))))

    def canary_traffic(poll_s):
        # self-driven canary traffic so weighted routing and shadow
        # diffs observe real forwards between ticks
        for i in range(4):
            registry.predict(config.name,
                             eval_set.features[i % n_eval:][:2])
        time.sleep(poll_s)

    pipe = ContinuousPipeline(
        registry, config.name, args.state_dir, config=config,
        buffer=buffer, eval_set=eval_set, metrics=metrics,
        alerts=alert_mgr, sample_input=features[:1],
        canary_wait=canary_traffic)
    signal.signal(signal.SIGTERM, lambda *a: pipe.request_stop())
    # a restarted process registers the ORIGINAL artifact as baseline;
    # if the journal already committed a promotion, re-apply it so the
    # resumed pipeline (and --modelOutputPath) serve the promoted weights
    restored = pipe.restore_promoted()
    if restored is not None:
        print(f"restored journaled promotion as v{restored}")

    try:
        # ONE stream pass per cycle (a real deployment points the route
        # at a broker): replaying all passes up front would let the
        # trainer's greedy drain starve later cycles into aborted runs
        summaries = []
        for _ in range(cycles):
            route = (Route().from_source(list(batches))
                     .to_callable(buffer.put).start())
            pipe.route = route
            summaries.append(pipe.run_cycle())
            route.join(timeout=60)
            if pipe.stopped:
                break
    finally:
        if alert_mgr is not None:
            alert_mgr.evaluate_once()
            alert_mgr.stop()
            firing = alert_mgr.firing()
            print(f"alerts firing at exit: {firing if firing else 'none'}")
        registry.shutdown()
        if args.log_json:
            from deeplearning4j_tpu.observe import (
                disable_structured_logging)
            disable_structured_logging()
    for s in summaries:
        print(f"run {s['run']}: {s['outcome']} "
              f"(live version {s['live_version']})")
    if args.modelOutputPath:
        served = registry.get(config.name)
        model_serializer.write_model(
            served.versions[served.current_version].model,
            args.modelOutputPath)
        print(f"wrote {args.modelOutputPath}")
    return summaries


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m deeplearning4j_tpu.cli "
              "{train,evaluate,serve,pipeline,nn-server,cloud-setup,"
              "profile} ...")
        return 0 if argv else 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "serve":
        serve_main(rest)
        return 0
    if cmd == "pipeline":
        pipeline_main(rest)
        return 0
    if cmd == "train":
        parallel_wrapper_main(rest)
        return 0
    if cmd == "evaluate":
        evaluate_main(rest)
        return 0
    if cmd == "profile":
        profile_main(rest)
        return 0
    if cmd == "nn-server":
        from deeplearning4j_tpu.clustering.server import NearestNeighborsServer
        server = NearestNeighborsServer.main(rest)
        print(f"nearest-neighbors server listening on port {server.port}")
        try:
            server._thread.join()
        except KeyboardInterrupt:
            server.stop()
        return 0
    if cmd == "cloud-setup":
        cluster_setup_main(rest)
        return 0
    print(f"unknown command {cmd!r}; expected 'train', 'evaluate', "
          "'serve', 'pipeline', 'nn-server', 'cloud-setup', or 'profile'")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
