"""scikit-learn adapter: Estimator-style wrappers around networks.

Role of the reference's Spark ML pipeline glue
(`dl4j-spark-ml/.../SparkDl4jNetwork.scala`, `AutoEncoder.scala` — exposing
DL4J nets as Spark ML `Pipeline` stages): in the Python ecosystem the
pipeline framework is scikit-learn, so networks are wrapped as
fit/predict/score estimators usable inside ``sklearn.pipeline.Pipeline``,
grid search, and cross-validation. No hard sklearn dependency — the wrappers
implement the estimator protocol structurally.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

try:  # inherit sklearn's estimator protocol (tags, clone) when available
    from sklearn.base import BaseEstimator as _SkBase
    from sklearn.base import ClassifierMixin as _SkClf
    from sklearn.base import RegressorMixin as _SkReg
except ImportError:  # structural protocol only
    _SkBase = object

    class _SkClf:  # type: ignore[no-redef]
        pass

    class _SkReg:  # type: ignore[no-redef]
        pass


class _BaseAdapter(_SkBase):
    def __init__(self, conf_factory: Callable[[int, int], object], *,
                 epochs: int = 10, batch_size: int = 32, shuffle: bool = True,
                 seed: int = 0):
        self.conf_factory = conf_factory
        self.epochs = epochs
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.network_ = None

    # sklearn protocol ----------------------------------------------------
    def get_params(self, deep: bool = True) -> dict:
        return {"conf_factory": self.conf_factory, "epochs": self.epochs,
                "batch_size": self.batch_size, "shuffle": self.shuffle,
                "seed": self.seed}

    def set_params(self, **params) -> "_BaseAdapter":
        valid = self.get_params()
        for k, v in params.items():
            if k not in valid:  # the sklearn contract: constructor params only
                raise ValueError(f"unknown parameter {k!r} "
                                 f"(valid: {sorted(valid)})")
            setattr(self, k, v)
        return self

    def _fit_net(self, x: np.ndarray, y2d: np.ndarray):
        from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = self.conf_factory(x.shape[-1], y2d.shape[-1])
        net = MultiLayerNetwork(conf) if not hasattr(conf, "vertices") else None
        if net is None:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            net = ComputationGraph(conf)
        net.init()
        it = ListDataSetIterator(DataSet(x, y2d), self.batch_size,
                                 shuffle=self.shuffle, seed=self.seed)
        net.fit(it, epochs=self.epochs)
        self.network_ = net
        return net

    def _output(self, x: np.ndarray) -> np.ndarray:
        if self.network_ is None:
            raise RuntimeError("fit must be called before predict")
        return np.asarray(self.network_.output(np.asarray(x, np.float32)))


class SklearnDl4jClassifier(_SkClf, _BaseAdapter):
    """Classifier estimator: ``conf_factory(n_features, n_classes)`` builds
    the network configuration (output layer = softmax + NLL)."""

    def fit(self, X, y) -> "SklearnDl4jClassifier":
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        if y.ndim == 1:
            self.classes_ = np.unique(y)  # sorted
            idx = np.searchsorted(self.classes_, y)
            onehot = np.zeros((len(y), len(self.classes_)), np.float32)
            onehot[np.arange(len(y)), idx] = 1.0
        else:
            self.classes_ = np.arange(y.shape[1])
            onehot = np.asarray(y, np.float32)
        self._fit_net(X, onehot)
        return self

    def predict_proba(self, X) -> np.ndarray:
        return self._output(X)

    def predict(self, X) -> np.ndarray:
        return self.classes_[np.argmax(self.predict_proba(X), axis=-1)]

    def score(self, X, y) -> float:
        return float(np.mean(self.predict(X) == np.asarray(y)))


class SklearnDl4jRegressor(_SkReg, _BaseAdapter):
    """Regressor estimator: ``conf_factory(n_features, n_outputs)`` builds
    the network (output layer = identity + MSE)."""

    def fit(self, X, y) -> "SklearnDl4jRegressor":
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        self._y1d = y.shape[1] == 1
        self._fit_net(X, y)
        return self

    def predict(self, X) -> np.ndarray:
        out = self._output(X)
        return out[:, 0] if self._y1d else out

    def score(self, X, y) -> float:
        """R^2, the sklearn regressor convention."""
        pred = self.predict(X)
        y = np.asarray(y, np.float32).reshape(pred.shape)
        ss_res = float(np.sum((y - pred) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2))
        return 1.0 - ss_res / max(ss_tot, 1e-12)
