"""Deterministic fault injection: make failure paths CI-provable.

The elastic supervisor (``parallel/elastic.py``) exists to survive
worker death, heartbeat stalls, torn checkpoints and lossy DCN links —
none of which occur naturally on a clean CI host. This module injects
those faults *deterministically* from a JSON ``FaultPlan`` so the
recovery choreography is exercised by ordinary subprocess CPU tests
instead of being demo-only:

- ``kill``: SIGKILL the worker process the moment it reports step S —
  a preemption without grace (the reference's fixed-membership design,
  ``SharedTrainingWrapper.java:131-156``, simply dies here).
- ``stall``: block inside step S (training and heartbeats both stop) —
  a hung host; the supervisor's heartbeat watchdog must kill + recover.
- ``stall_heartbeat``: suppress heartbeats from step S on while training
  continues — a partitioned/zombie worker; the supervisor must fence it.
- ``corrupt_checkpoint``: truncate or overwrite checkpoint files right
  after the save at step S commits — exercises the restore-time
  integrity fallback (``OrbaxCheckpointManager.restore(fallback=True)``).
  The checkpoint is a world-level artifact written by whichever rank is
  0 at that step, so this fault matches on ``step`` alone (``worker``
  is accepted but ignored).
- ``drop_dcn`` / ``duplicate_dcn``: drop or duplicate the Nth outbound
  cross-slice gradient frame (``parallel/dcn.py``) — lossy UDP-ish
  transport semantics.

Host-scoped faults (pod-scale failure domains; a "host" in CI is a
process group the elastic supervisor forms on localhost):

- ``kill_host``: SIGKILL every worker of host group H the moment it
  reports step S — a whole machine disappearing, the failure domain the
  per-worker ``kill`` cannot express.
- ``partition``: from step S on, host group H is cut from the rest of
  the job — its workers block inside the step (a collective across the
  partition can never complete) while their background heartbeats stay
  alive, and every DCN frame crossing the boundary is dropped in both
  directions. The signature the supervisor's step-progress watchdog
  keys on: liveness without progress.
- ``slow_save``: stall the asynchronous checkpoint thread for
  ``duration_s`` during the save of step S — a slow/hung filesystem;
  training must keep overlapping and the bounded in-flight window must
  backpressure instead of accumulating torn saves.
- ``kill`` additionally accepts a ``phase`` field
  (``pre_write | mid_shard | pre_stamp``): instead of firing on the
  training step, the SIGKILL lands at that point of the checkpoint
  commit protocol — the torn-async-save matrix.

Serving-scoped faults (the serving chaos harness; a "model" here is a
registered serving name, the sequence a per-model request/forward
counter):

- ``crash_forward``: the model's forward raises a non-``Exception``
  error at dispatch sequence S — the batching dispatcher thread DIES
  (the containment seam ``ParallelInference._run`` exists for), which
  is what trips restart supervision and the per-version circuit
  breaker. Keyed on the per-model *forward* sequence (dispatches, not
  HTTP requests — a coalesced batch is one forward).
- ``slow_forward``: the forward at dispatch sequence S blocks for
  ``duration_s`` — a latency spike; drives deadline/brownout paths.
- ``reject_admission``: the HTTP front-end sheds request S (per-model
  *request* sequence) at the door with 429 + ``Retry-After`` — a
  simulated overload the resilient client must absorb via its retry
  budget.
- ``drop_response``: the front-end processes request S fully, then
  severs the connection without writing the response — the network
  eating an answer; proves the client's reconnect + retry path.

Activation: set ``DL4J_TPU_FAULT_PLAN`` to a plan file path (or inline
JSON) before the process starts. When the variable is unset every hook
is a single-``is None``-check no-op — the production hot path pays one
attribute load and a comparison, nothing else.

Faults are keyed on (worker slot, step/seq) — host faults on (host
group, step/seq), serving faults on (model name, request/forward seq):
pure functions of training/traffic progress, so a plan replays
identically on every run — which is what lets tests assert exact
recovery points. The process's own host group arrives through
``DL4J_TPU_ELASTIC_HOST`` (or :func:`set_host`).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Dict, List, Optional

ENV_VAR = "DL4J_TPU_FAULT_PLAN"
ENV_HOST_VAR = "DL4J_TPU_ELASTIC_HOST"

FAULT_TYPES = ("kill", "stall", "stall_heartbeat", "corrupt_checkpoint",
               "drop_dcn", "duplicate_dcn",
               "kill_host", "partition", "slow_save",
               "crash_forward", "slow_forward", "reject_admission",
               "drop_response")
HOST_FAULT_TYPES = ("kill_host", "partition")
SERVING_FAULT_TYPES = ("crash_forward", "slow_forward", "reject_admission",
                       "drop_response")
CORRUPT_MODES = ("truncate", "garbage", "delete")
SAVE_PHASES = ("pre_write", "mid_shard", "pre_stamp")


class InjectedDispatcherCrash(BaseException):
    """``crash_forward``'s payload. Deliberately NOT an ``Exception``:
    a model error is contained per request (the 500 path), but this must
    escape ``ParallelInference._dispatch_batch``'s per-request handler
    and kill the dispatcher thread itself — the failure mode the
    supervision/breaker machinery exists for."""


@dataclasses.dataclass
class Fault:
    """One planned fault. ``worker`` is the elastic SLOT id (stable across
    restarts and renumbering), ``step`` the global training iteration (or
    checkpoint step for ``corrupt_checkpoint``, frame sequence number for
    the DCN faults). Host-scoped faults carry ``host`` (the failure
    domain) instead of a meaningful ``worker``; a ``kill``/``slow_save``
    may carry ``phase`` to fire inside the checkpoint commit protocol
    rather than on the training step."""

    type: str
    worker: object  # int slot, or "*" for any worker
    step: int
    mode: str = "truncate"        # corrupt_checkpoint only
    duration_s: float = 3600.0    # stall/partition/slow_save only
    signum: int = int(signal.SIGKILL)
    host: object = None           # kill_host / partition failure domain
    phase: Optional[str] = None   # kill/slow_save: commit-protocol phase
    model: object = None          # serving faults: model name, or "*"

    def matches(self, worker, step: int) -> bool:
        return (self.worker == "*" or self.worker == worker) \
            and int(step) == int(self.step)

    def matches_host(self, host, step: int) -> bool:
        return host is not None \
            and (self.host == "*" or self.host == host) \
            and int(step) == int(self.step)

    def matches_model(self, model, seq: int) -> bool:
        return (self.model == "*" or self.model == model) \
            and int(seq) == int(self.step)


class FaultPlan:
    """A validated list of :class:`Fault` entries."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)

    # -- construction / validation --------------------------------------
    @classmethod
    def parse(cls, spec) -> "FaultPlan":
        """Build from a parsed dict; raises ``ValueError`` with the
        offending fault index on any schema problem."""
        if not isinstance(spec, dict) or "faults" not in spec:
            raise ValueError(
                "fault plan must be an object with a 'faults' list")
        raw = spec["faults"]
        if not isinstance(raw, list):
            raise ValueError("'faults' must be a list")
        faults = []
        for i, f in enumerate(raw):
            if not isinstance(f, dict):
                raise ValueError(f"fault[{i}]: must be an object")
            unknown = set(f) - {"type", "worker", "step", "mode",
                                "duration_s", "signal", "host", "phase",
                                "model"}
            if unknown:
                raise ValueError(
                    f"fault[{i}]: unknown field(s) {sorted(unknown)}")
            ftype = f.get("type")
            if ftype not in FAULT_TYPES:
                raise ValueError(
                    f"fault[{i}]: unknown type {ftype!r} "
                    f"(one of {', '.join(FAULT_TYPES)})")
            model = f.get("model")
            if ftype in SERVING_FAULT_TYPES:
                if not (isinstance(model, str) and model):
                    raise ValueError(
                        f"fault[{i}]: {ftype} needs a 'model' name "
                        f"(a registered serving name, or '*'), "
                        f"got {model!r}")
                for bad in ("worker", "host", "phase", "mode"):
                    if bad in f:
                        raise ValueError(
                            f"fault[{i}]: {bad!r} is not valid on the "
                            f"serving fault {ftype} (keyed on model + "
                            f"request/forward seq)")
            elif model is not None:
                raise ValueError(
                    f"fault[{i}]: 'model' is only valid on "
                    f"{'/'.join(SERVING_FAULT_TYPES)}, not {ftype}")
            worker = f.get("worker", "*")
            ok = worker == "*" or (isinstance(worker, int) and worker >= 0) \
                or (isinstance(worker, str) and worker)
            if not ok:
                raise ValueError(
                    f"fault[{i}]: worker must be a slot index >= 0, a "
                    f"slice-id string, or '*', got {worker!r}")
            host = f.get("host")
            if ftype in HOST_FAULT_TYPES:
                if ftype == "kill_host":
                    host_ok = host == "*" \
                        or (isinstance(host, int) and host >= 0)
                else:  # partition: "*" would cut everyone from everyone
                    host_ok = isinstance(host, int) and host >= 0
                if not host_ok:
                    raise ValueError(
                        f"fault[{i}]: {ftype} needs a host group index "
                        f">= 0{' (or *)' if ftype == 'kill_host' else ''}, "
                        f"got {host!r}")
            elif ftype == "slow_save" and host is not None:
                # optionally host-scoped: stall the saver thread of every
                # worker on one host (worker matching is ignored then)
                if not (host == "*" or (isinstance(host, int) and host >= 0)):
                    raise ValueError(
                        f"fault[{i}]: slow_save host must be a host group "
                        f"index >= 0 or '*', got {host!r}")
            elif host is not None:
                raise ValueError(
                    f"fault[{i}]: 'host' is only valid on "
                    f"{'/'.join(HOST_FAULT_TYPES)}/slow_save, not {ftype}")
            phase = f.get("phase")
            if phase is not None:
                if ftype not in ("kill", "kill_host", "slow_save"):
                    raise ValueError(
                        f"fault[{i}]: 'phase' is only valid on "
                        f"kill/kill_host/slow_save, not {ftype}")
                if phase not in SAVE_PHASES:
                    raise ValueError(
                        f"fault[{i}]: unknown save phase {phase!r} "
                        f"(one of {', '.join(SAVE_PHASES)})")
            step = f.get("step")
            if not isinstance(step, int) or step < 0:
                raise ValueError(
                    f"fault[{i}]: step must be an int >= 0, got {step!r}")
            mode = f.get("mode", "truncate")
            if ftype == "corrupt_checkpoint" and mode not in CORRUPT_MODES:
                raise ValueError(
                    f"fault[{i}]: corrupt mode {mode!r} "
                    f"(one of {', '.join(CORRUPT_MODES)})")
            duration = f.get("duration_s", 3600.0)
            if not isinstance(duration, (int, float)) or duration < 0:
                raise ValueError(
                    f"fault[{i}]: duration_s must be >= 0, got {duration!r}")
            signame = f.get("signal", "KILL")
            try:
                signum = int(getattr(signal, f"SIG{signame}"))
            except (AttributeError, TypeError):
                raise ValueError(
                    f"fault[{i}]: unknown signal {signame!r}") from None
            faults.append(Fault(type=ftype, worker=worker, step=step,
                                mode=mode, duration_s=float(duration),
                                signum=signum, host=host, phase=phase,
                                model=model))
        return cls(faults)

    @classmethod
    def load(cls, spec: str) -> "FaultPlan":
        """From a file path or an inline JSON string."""
        text = spec
        if not spec.lstrip().startswith("{"):
            with open(spec, "r", encoding="utf-8") as fh:
                text = fh.read()
        return cls.parse(json.loads(text))

    def lint(self) -> List[str]:
        """Dry-run lint (no fault is executed): duplicate triggers and
        shadowed entries that can never fire."""
        problems: List[str] = []
        seen: Dict[tuple, int] = {}
        for i, f in enumerate(self.faults):
            key = (f.type, f.worker, f.host, f.step, f.phase, f.model)
            if key in seen:
                problems.append(
                    f"fault[{i}] duplicates fault[{seen[key]}]: "
                    f"{f.type} worker={f.worker} step={f.step}")
            seen[key] = i
        # a kill/stall at step S shadows any later-step fault on the same
        # worker within the same generation
        fatal = {}
        for i, f in enumerate(self.faults):
            if f.type in ("kill", "stall") and f.worker != "*":
                cur = fatal.get(f.worker)
                if cur is None or f.step < cur[1]:
                    fatal[f.worker] = (i, f.step)
        for i, f in enumerate(self.faults):
            if f.worker == "*" or f.type in ("kill", "stall"):
                continue
            hit = fatal.get(f.worker)
            if hit is not None and f.step > hit[1] \
                    and f.type in ("stall_heartbeat", "slow_save"):
                problems.append(
                    f"fault[{i}] ({f.type} worker={f.worker} step={f.step}) "
                    f"can never fire: fault[{hit[0]}] kills/stalls that "
                    f"worker at step {hit[1]} first")
        # same shadowing at host-group granularity: a kill_host/partition
        # at step S ends that host's generation — a later-step host fault
        # on the SAME host can never fire within it
        fatal_host = {}
        for i, f in enumerate(self.faults):
            if f.type in HOST_FAULT_TYPES and f.host != "*":
                cur = fatal_host.get(f.host)
                if cur is None or f.step < cur[1]:
                    fatal_host[f.host] = (i, f.step)
        for i, f in enumerate(self.faults):
            if f.type not in HOST_FAULT_TYPES or f.host == "*":
                continue
            hit = fatal_host.get(f.host)
            if hit is not None and i != hit[0] and f.step > hit[1]:
                problems.append(
                    f"fault[{i}] ({f.type} host={f.host} step={f.step}) "
                    f"can never fire: fault[{hit[0]}] kills/partitions that "
                    f"host at step {hit[1]} first")
        # serving shadows are same-sequence, not later-step (dispatchers
        # restart, so a crash does not end the timeline): an admission
        # rejection at request S means the response path for S is never
        # reached, and a crash_forward at dispatch S fires before a
        # slow_forward stall of the same dispatch ever starts
        by_key: Dict[tuple, int] = {}
        for i, f in enumerate(self.faults):
            if f.type in SERVING_FAULT_TYPES:
                by_key.setdefault((f.type, f.model, f.step), i)
        for i, f in enumerate(self.faults):
            if f.type == "drop_response":
                hit = by_key.get(("reject_admission", f.model, f.step))
                if hit is not None:
                    problems.append(
                        f"fault[{i}] (drop_response model={f.model} "
                        f"seq={f.step}) can never fire: fault[{hit}] "
                        f"rejects that request at admission first")
            elif f.type == "slow_forward":
                hit = by_key.get(("crash_forward", f.model, f.step))
                if hit is not None:
                    problems.append(
                        f"fault[{i}] (slow_forward model={f.model} "
                        f"seq={f.step}) can never fire: fault[{hit}] "
                        f"crashes that dispatch first")
        return problems

    def find(self, ftype: str, worker, step: int) -> Optional[Fault]:
        for f in self.faults:
            if f.type == ftype and f.matches(worker, step):
                return f
        return None


# -- process-wide activation -------------------------------------------------

_plan: Optional[FaultPlan] = None
if os.environ.get(ENV_VAR):
    _plan = FaultPlan.load(os.environ[ENV_VAR])

# this process's host group (failure domain); host-scoped faults are
# inert in processes that never learned theirs
_host: Optional[int] = None
if os.environ.get(ENV_HOST_VAR, "").isdigit():
    _host = int(os.environ[ENV_HOST_VAR])

# injectable for tests: on_step's kill must be observable without dying
_kill = os.kill
_sleep = time.sleep


def active_plan() -> Optional[FaultPlan]:
    return _plan


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Activate a plan in-process (tests); ``None`` deactivates."""
    global _plan
    _plan = plan


def current_host() -> Optional[int]:
    return _host


def set_host(host: Optional[int]) -> None:
    """Declare this process's host group (``None`` = unknown)."""
    global _host
    _host = None if host is None else int(host)


# -- hooks (each begins with the single is-None check) -----------------------

def on_step(worker, step: int, host=None) -> None:
    """Call once per completed training iteration. May not return (kill),
    or may block for a long time (stall / partition)."""
    if _plan is None:
        return
    host = _host if host is None else host
    for f in _plan.faults:
        # phase-scoped kills belong to on_save_phase; skipping them here
        # (rather than taking the first kill match) keeps a plan that
        # lists both a phase kill and a plain kill for the same (worker,
        # step) firing both
        if f.phase is not None:
            continue
        if f.type == "kill" and f.matches(worker, step):
            _kill(os.getpid(), f.signum)
            return
        if f.type == "kill_host" and f.matches_host(host, step):
            _kill(os.getpid(), f.signum)
            return
    f = _plan.find("stall", worker, step)
    if f is not None:
        _sleep(f.duration_s)
        return
    # partition: this host is cut off — a collective across the boundary
    # can never complete, so the step blocks while (background)
    # heartbeats stay alive. Sticky from the configured step onward.
    for f in _plan.faults:
        if f.type == "partition" and host is not None and f.host == host \
                and int(step) >= int(f.step):
            _sleep(f.duration_s)
            return


def on_save_phase(worker, step: int, phase: str, host=None) -> None:
    """Call at each phase of the checkpoint commit protocol
    (``pre_write`` → own shard about to be written, ``mid_shard`` → own
    shard landed / model write not finalized, ``pre_stamp`` → everything
    finalized, commit stamp not yet written). Applies phase-scoped kills
    (the torn-async-save matrix) and ``slow_save`` stalls (a slow
    filesystem; fires at ``pre_write`` unless the fault names a phase)."""
    if _plan is None:
        return
    host = _host if host is None else host
    for f in _plan.faults:
        if f.type == "kill" and f.phase == phase and f.matches(worker, step):
            _kill(os.getpid(), f.signum)
            return
        if f.type == "kill_host" and f.phase == phase \
                and f.matches_host(host, step):
            _kill(os.getpid(), f.signum)
            return
        if f.type == "slow_save" and (f.phase or "pre_write") == phase:
            # a host field scopes the stall to that host group (worker
            # matching is ignored then — the default worker "*" would
            # otherwise stall everyone)
            hit = f.matches_host(host, step) if f.host is not None \
                else f.matches(worker, step)
            if hit:
                _sleep(f.duration_s)


def partition_active(host_a, host_b, seq: int) -> bool:
    """Are host groups ``a`` and ``b`` separated at sequence/step
    ``seq``? True when a planned partition has cut either side off."""
    if _plan is None or host_a is None or host_b is None \
            or host_a == host_b:
        return False
    for f in _plan.faults:
        if f.type == "partition" and int(seq) >= int(f.step) \
                and f.host in (host_a, host_b):
            return True
    return False


def on_heartbeat(worker, step: int) -> bool:
    """True → emit the heartbeat; False → suppress it (zombie worker).
    Suppression is sticky from the configured step onward — a stalled
    heartbeat does not resume."""
    if _plan is None:
        return True
    for f in _plan.faults:
        if f.type == "stall_heartbeat" \
                and (f.worker == "*" or f.worker == worker) \
                and int(step) >= int(f.step):
            return False
    return True


def on_checkpoint_saved(worker, step: int, directory: str) -> None:
    """Call right after a checkpoint at ``step`` commits under
    ``directory``; applies any planned corruption to the files just
    written. The model checkpoint is a WORLD-level artifact written by
    whichever rank is 0 when step ``step`` commits, so the fault's
    ``worker`` field is ignored here — matching on it would make a
    fault targeting a non-rank-0 slot silently never fire."""
    if _plan is None:
        return
    for f in _plan.faults:
        if f.type == "corrupt_checkpoint" and int(step) == int(f.step):
            corrupt_checkpoint(directory, mode=f.mode)
            return


def on_dcn_send(worker, seq: int, frame: bytes,
                host=None) -> List[bytes]:
    """Transform one outbound DCN frame: ``[]`` drops it, two copies
    duplicate it, ``[frame]`` passes through. ``host`` is accepted for
    call symmetry with :func:`on_dcn_recv`; a partition is enforced at
    the RECEIVER, where the cut is destination-aware — a sender cannot
    know which of its (possibly fanned-out) recipients sit across the
    boundary, and a blanket sender-side drop would sever intra-host
    links the partition model defines as uncut."""
    if _plan is None:
        return [frame]
    if _plan.find("drop_dcn", worker, seq) is not None:
        return []
    if _plan.find("duplicate_dcn", worker, seq) is not None:
        return [frame, frame]
    return [frame]


def on_dcn_recv(worker, seq: int, frame_host=None, host=None) -> bool:
    """True → deliver the inbound frame; False → drop it (the sender is
    on the far side of an active partition). Covers the direction
    ``on_dcn_send`` cannot: frames already in flight from a peer the
    partition has since cut off."""
    if _plan is None:
        return True
    host = _host if host is None else host
    return not partition_active(host, frame_host, seq)


def on_forward(model: str, seq: int) -> None:
    """Call once per dispatched forward of serving ``model`` (dispatch
    sequence ``seq``). May raise :class:`InjectedDispatcherCrash`
    (``crash_forward`` — kills the dispatcher thread) or block for
    ``duration_s`` (``slow_forward``). A crash shadows a stall planned
    for the same dispatch."""
    if _plan is None:
        return
    for f in _plan.faults:
        if f.type == "crash_forward" and f.matches_model(model, seq):
            raise InjectedDispatcherCrash(
                f"injected crash_forward: {model} forward #{seq}")
    for f in _plan.faults:
        if f.type == "slow_forward" and f.matches_model(model, seq):
            _sleep(f.duration_s)
            return


def on_admission(model: str, seq: int) -> bool:
    """True → admit request ``seq`` of ``model``; False → the front-end
    sheds it with 429 + ``Retry-After`` (``reject_admission``: a
    simulated overload the client's retry budget must absorb)."""
    if _plan is None:
        return True
    for f in _plan.faults:
        if f.type == "reject_admission" and f.matches_model(model, seq):
            return False
    return True


def on_response(model: str, seq: int) -> bool:
    """True → write the response for request ``seq``; False → the
    front-end severs the connection after doing the work
    (``drop_response``: the network ate the answer — the client must
    reconnect and retry)."""
    if _plan is None:
        return True
    for f in _plan.faults:
        if f.type == "drop_response" and f.matches_model(model, seq):
            return False
    return True


# -- shared corruption implementation ---------------------------------------

def corrupt_checkpoint(path: str, mode: str = "truncate") -> List[str]:
    """Damage a checkpoint on disk; returns the files touched.

    ``path`` may be a single file (zip checkpoint) or a directory (an
    orbax step dir) — directories are walked and every regular file
    is damaged, so the restore cannot quietly succeed off an
    untouched shard. Modes: ``truncate`` (keep the first half),
    ``garbage`` (overwrite the middle with 0xFF), ``delete`` (unlink).
    """
    if mode not in CORRUPT_MODES:
        raise ValueError(f"unknown corrupt mode {mode!r}")
    targets: List[str] = []
    if os.path.isdir(path):
        for root, _dirs, files in os.walk(path):
            targets.extend(os.path.join(root, f) for f in sorted(files))
    elif os.path.exists(path):
        targets.append(path)
    else:
        raise FileNotFoundError(f"no checkpoint at {path}")
    touched = []
    for t in targets:
        if mode == "delete":
            os.unlink(t)
            touched.append(t)
            continue
        size = os.path.getsize(t)
        with open(t, "r+b") as fh:
            if mode == "truncate":
                fh.truncate(max(0, size // 2))
            else:  # garbage
                fh.seek(max(0, size // 4))
                fh.write(b"\xff" * max(1, size // 2))
        touched.append(t)
    return touched
