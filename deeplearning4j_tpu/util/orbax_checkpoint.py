"""Orbax-backed checkpointing — the TPU-scale checkpoint path.

The zip checkpoints (``util/model_serializer.py``) are the
DL4J-compatible interchange (``ModelSerializer.java:51`` role). This
module adds the idiomatic JAX path on top: the same model state (params
+ updater state + training counters + config JSON) stored through
``orbax.checkpoint``, which brings sharding-aware, per-host-parallel,
optionally async writes and step-managed retention — what checkpointing
a multi-host mesh actually needs (CheckpointListener rotation at pod
scale). Restore returns a fully wired MultiLayerNetwork /
ComputationGraph, like the zip restore does.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = [
    "save_model",
    "restore_model",
    "snapshot_state",
    "AsyncSaveHandle",
    "OrbaxCheckpointManager",
]

_CONFIG_FILE = "model_config.json"


def _canonical_dir(directory: str) -> str:
    """Absolutize local paths; leave URL-style paths (``gs://…``)
    untouched — ``os.path.abspath('gs://b/ckpt')`` would mangle them
    into ``<cwd>/gs:/b/ckpt`` and silently redirect cloud saves to a
    bogus local directory. Scheme paths flow through etils ``epath``,
    which handles both existence checks and mkdir for remote stores."""
    if "://" in directory:
        return directory
    return os.path.abspath(directory)


# -- shared helpers ----------------------------------------------------------

def _write_meta(model, directory: str) -> None:
    # primary-host-gated like orbax's own writes (every process calling
    # save on a pod must not race on the shared meta file), and through
    # epath so gs:// checkpoint directories work like local ones
    import jax
    if jax.process_index() != 0:
        return
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from etils import epath
    from deeplearning4j_tpu.nn.layers.attention import QKV_LAYOUT
    kind = "mln" if isinstance(model, MultiLayerNetwork) else "graph"
    d = epath.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    (d / _CONFIG_FILE).write_text(
        json.dumps({"kind": kind, "conf": json.loads(model.conf.to_json()),
                    "qkv_layout": QKV_LAYOUT}))


def _build_model(directory: str):
    from etils import epath
    meta = json.loads((epath.Path(directory) / _CONFIG_FILE).read_text())
    if meta["kind"] == "mln":
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        model = MultiLayerNetwork(
            MultiLayerConfiguration.from_dict(meta["conf"]))
    else:
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        model = ComputationGraph(
            ComputationGraphConfiguration.from_dict(meta["conf"]))
    model.init()  # allocates the target pytree structure + updaters
    # pre-round-5 checkpoints carry no qkv_layout stamp: their fused
    # attention columns are block-major and must be repacked after the
    # state is applied (_apply_state reads this flag)
    from deeplearning4j_tpu.nn.layers.attention import QKV_LAYOUT
    model._legacy_qkv_checkpoint = meta.get("qkv_layout") != QKV_LAYOUT
    return model


def _multiprocess_safe(tree):
    """In a multi-process (``jax.distributed``) job, orbax refuses to
    serialize HOST-LOCAL jax.Arrays (replicated lockstep state, like the
    deterministic-broadcast training masters keep) — only numpy or global
    sharded arrays. Convert fully-addressable arrays to host numpy;
    genuinely global (multi-host sharded) arrays pass through to orbax's
    proper sharded path."""
    import jax
    if jax.process_count() <= 1:
        return tree

    def conv(x):
        if isinstance(x, jax.Array):
            if x.is_fully_addressable:
                return np.asarray(x)
            if x.is_fully_replicated:
                # global replicated array (e.g. params after training over
                # a multi-process mesh): every process holds the full
                # value in its local shard
                return np.asarray(x.addressable_data(0))
        return x
    return jax.tree_util.tree_map(conv, tree)


def _state_pytree(model, with_updater: bool) -> Dict[str, Any]:
    state: Dict[str, Any] = {"params": model.params, "states": model.states}
    if with_updater and model.updater_states is not None:
        state["updater_states"] = model.updater_states
    state["counters"] = {"iteration": np.asarray(model.iteration),
                         "epoch": np.asarray(model.epoch)}
    return _multiprocess_safe(state)


def snapshot_state(model, with_updater: bool = True) -> Dict[str, Any]:
    """Decouple a checkpoint from the live model: the state pytree with
    every addressable array copied to host numpy at call time. Training
    may then mutate the model while a background thread feeds the
    snapshot to :meth:`OrbaxCheckpointManager.save` — the overlapped
    (async) elastic checkpoint path. Non-addressable global arrays (a
    genuinely multi-host-sharded model) pass through untouched; those
    must go through orbax's own sharded async machinery instead."""
    import jax

    def conv(x):
        if isinstance(x, jax.Array):
            if x.is_fully_addressable:
                return np.asarray(x).copy()
            return x
        if isinstance(x, np.ndarray):
            return x.copy()
        return x
    return jax.tree_util.tree_map(
        conv, _state_pytree(model, with_updater=with_updater))


def _template_for(model, metadata) -> Dict[str, Any]:
    """Restore template matching what the checkpoint actually contains
    (a template/on-disk structure mismatch is a hard orbax error)."""
    has_updater = True
    try:
        tree = getattr(metadata, "item_metadata", metadata)
        tree = getattr(tree, "tree", tree)
        if hasattr(tree, "keys"):
            has_updater = "updater_states" in tree
    except Exception:  # noqa: BLE001 - fall back to assuming present
        pass
    return _state_pytree(model, with_updater=has_updater)


def _sharded_template(model, template: Dict[str, Any], mesh,
                      rules=None) -> Dict[str, Any]:
    """Rewrite the params/updater_states halves of a restore template as
    ``ShapeDtypeStruct``s carrying the rule-derived target shardings, so
    orbax restores each leaf DIRECTLY into its mesh placement — the
    reshard-on-restore path (a 2×4 checkpoint restored onto a 1×4 mesh
    re-slices shards; no full-host materialization on the pod path).
    ``states``/``counters`` stay as-is (replicated small state)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.parallel.sharding import (
        DEFAULT_2D_RULES, _leaf_sharding_ok, _path_name,
        match_partition_rules)

    specs = match_partition_rules(
        DEFAULT_2D_RULES if rules is None else rules, model.params)
    placed: Dict[str, Any] = {}

    def conv_param(path, v, spec):
        if not _leaf_sharding_ok(v.shape, spec, mesh):
            spec = P()
        placed[_path_name(path)] = (tuple(v.shape), spec)
        return jax.ShapeDtypeStruct(v.shape, v.dtype,
                                    sharding=NamedSharding(mesh, spec))

    out = dict(template)
    out["params"] = jax.tree_util.tree_map_with_path(
        conv_param, template["params"], specs)
    if "updater_states" in out:
        def conv_upd(path, s):
            shape_spec = placed.get(_path_name(path[:-1]))
            spec = (shape_spec[1] if shape_spec is not None
                    and tuple(s.shape) == shape_spec[0] else P())
            return jax.ShapeDtypeStruct(s.shape, s.dtype,
                                        sharding=NamedSharding(mesh, spec))
        out["updater_states"] = jax.tree_util.tree_map_with_path(
            conv_upd, template["updater_states"])
    return out


def _apply_state(model, state: Dict[str, Any], load_updater: bool):
    model.params = state["params"]
    model.states = state["states"]
    if load_updater and "updater_states" in state:
        model.updater_states = state["updater_states"]
    counters = state.get("counters", {})
    model.iteration = int(np.asarray(counters.get("iteration", 0)))
    model.epoch = int(np.asarray(counters.get("epoch", 0)))
    if getattr(model, "_legacy_qkv_checkpoint", False):
        from deeplearning4j_tpu.nn.layers.attention import (
            repack_legacy_fused_qkv)
        repack_legacy_fused_qkv(model)
        model._legacy_qkv_checkpoint = False
    return model


# -- one-shot save / restore -------------------------------------------------

class AsyncSaveHandle:
    """Returned by ``save_model(..., async_write=True)``: the write runs
    in the background; call :meth:`wait_until_finished` (or use as a
    context manager) before reading the checkpoint or exiting."""

    def __init__(self, checkpointer):
        self._ckptr = checkpointer

    def wait_until_finished(self) -> None:
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
            self._ckptr.close()
            self._ckptr = None

    def __enter__(self) -> "AsyncSaveHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.wait_until_finished()


def save_model(model, directory: str, *, save_updater: bool = True,
               async_write: bool = False) -> Optional[AsyncSaveHandle]:
    """Write a model checkpoint into ``directory`` via orbax.

    ``async_write=True`` returns an :class:`AsyncSaveHandle` as soon as
    the device arrays are snapshotted — training continues while bytes
    hit disk; call ``handle.wait_until_finished()`` before relying on
    the files. Synchronous saves return None.
    """
    import orbax.checkpoint as ocp

    directory = _canonical_dir(directory)
    _write_meta(model, directory)

    state = _state_pytree(model, with_updater=save_updater)
    target = os.path.join(directory, "state")
    if async_write:
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        ckptr.save(target, args=ocp.args.StandardSave(state), force=True)
        return AsyncSaveHandle(ckptr)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(target, args=ocp.args.StandardSave(state), force=True)
    return None


def restore_model(directory: str, *, load_updater: bool = True,
                  mesh=None, sharding_rules=None):
    """Restore a model saved by :func:`save_model`. Works regardless of
    whether the checkpoint contains updater state.

    ``mesh`` (+ optional ``sharding_rules``) restores STRAIGHT INTO a
    rule-sharded placement on that mesh — the checkpoint's own mesh
    shape is irrelevant (reshard-on-restore: a 2×4 save restores onto a
    1×4 mesh), and the returned model has ``fit``/``output`` honoring
    the mesh exactly as after
    :func:`deeplearning4j_tpu.parallel.sharding.shard_model_with_rules`."""
    import orbax.checkpoint as ocp

    directory = _canonical_dir(directory)
    model = _build_model(directory)
    target = os.path.join(directory, "state")
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        template = _template_for(model, ckptr.metadata(target))
        if mesh is not None:
            template = _sharded_template(model, template, mesh,
                                         sharding_rules)
        state = ckptr.restore(target,
                              args=ocp.args.StandardRestore(template))
    model = _apply_state(model, state, load_updater)
    if mesh is not None:
        from deeplearning4j_tpu.parallel.sharding import (
            shard_model_with_rules)
        shard_model_with_rules(model, mesh, sharding_rules)
    return model


# -- step-managed rotation ---------------------------------------------------

class OrbaxCheckpointManager:
    """Step-managed rotation over orbax (CheckpointListener's
    keepLast/saveEvery semantics at pod scale, via
    ``ocp.CheckpointManager``)."""

    def __init__(self, directory: str, *, max_to_keep: Optional[int] = 3,
                 save_interval_steps: int = 1,
                 active_processes: Optional[set] = None,
                 barrier_sync_key_prefix: Optional[str] = None):
        """``active_processes`` restricts orbax's multihost coordination to
        a subset of a ``jax.distributed`` job (e.g. ``{0}`` so only the
        coordinator checkpoints replicated state) — without it, a save
        from one process of a multi-process job hangs on a barrier the
        other processes never enter. ``barrier_sync_key_prefix`` keeps
        two concurrent managers' barriers from colliding."""
        import orbax.checkpoint as ocp
        from etils import epath
        self.directory = _canonical_dir(directory)
        epath.Path(self.directory).mkdir(parents=True, exist_ok=True)
        mp_options = None
        if active_processes is not None or barrier_sync_key_prefix is not None:
            primary = (min(active_processes) if active_processes else 0)
            mp_options = ocp.options.MultiprocessingOptions(
                primary_host=primary,
                active_processes=active_processes,
                barrier_sync_key_prefix=barrier_sync_key_prefix)
        extra = {}
        if mp_options is not None:
            # orbax treats an explicit None differently from the kwarg
            # being absent, and refuses create=True with active_processes;
            # the epath mkdir above has already made the root either way
            extra = {"multiprocessing_options": mp_options, "create": False}
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=max(1, save_interval_steps),
            **extra)
        self._mgr = ocp.CheckpointManager(self.directory,
                                          options=self._options)
        self._meta_written = False

    def save(self, step: int, model, *, save_updater: bool = True,
             overwrite_existing: bool = False,
             state: Optional[Dict[str, Any]] = None) -> bool:
        """Save at ``step`` (skipped when the interval says so; returns
        whether a save happened).

        ``overwrite_existing=True``: orbax returns False (writing
        NOTHING) when a finalized dir for ``step`` already exists — e.g.
        a corrupt leftover a fallback restore walked past. The elastic
        commit path must not re-advertise those bytes as freshly saved,
        so this deletes the stale step dir and saves again.

        ``state``: a pre-built state pytree (see :func:`snapshot_state`)
        written INSTEAD of reading the live model — the async save path,
        where ``model`` is only consulted for its immutable config meta
        while training keeps mutating its arrays."""
        import orbax.checkpoint as ocp

        def _save():
            return self._mgr.save(
                step, args=ocp.args.StandardSave(
                    state if state is not None
                    else _state_pytree(model, with_updater=save_updater)))

        if not self._meta_written:
            _write_meta(model, self.directory)
            self._meta_written = True
        ok = _save()
        if not ok and overwrite_existing \
                and int(step) in set(self.all_steps()):
            import shutil
            shutil.rmtree(os.path.join(self.directory, str(int(step))),
                          ignore_errors=True)
            if hasattr(self._mgr, "reload"):
                self._mgr.reload()  # drop the cached step list
            ok = _save()
        return ok

    def all_steps(self) -> List[int]:
        """Steps currently retained by the rotation, ascending."""
        return sorted(int(s) for s in self._mgr.all_steps())

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    #: step actually restored by the last :meth:`restore` call — differs
    #: from the requested step when ``fallback`` walked to an older one
    restored_step: Optional[int] = None

    def restore(self, step: Optional[int] = None, *,
                load_updater: bool = True, fallback: bool = False,
                fallback_steps: Optional[Sequence[int]] = None,
                mesh=None, sharding_rules=None):
        """Restore the model at ``step`` (default: latest).

        ``mesh``/``sharding_rules`` restore straight into a rule-sharded
        placement regardless of the mesh the checkpoint was saved under
        (see :func:`restore_model` — the elastic reshard-on-shrink path).

        ``fallback=True`` is the integrity-tolerant path: when the chosen
        step is truncated/corrupt (a preemption mid-write, a fault-
        injected torn checkpoint), restore walks back through the older
        retained steps instead of failing — the rotation (``max_to_keep``)
        exists precisely so the previous good step survives. The step
        actually used is recorded in :attr:`restored_step`. Without
        fallback a damaged checkpoint fails fast with a clear error.

        ``fallback_steps`` restricts the walk to an allow-list (the
        elastic supervisor passes its fence-eligible steps: an orbax dir
        may hold steps a zombie generation wrote after its fence, and the
        fallback must not resurrect them)."""
        steps = sorted(self._mgr.all_steps())
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise ValueError(f"no checkpoints in {self.directory}")
        candidates = [step]
        if fallback:
            pool = steps if fallback_steps is None else \
                [s for s in steps if s in set(int(x) for x in fallback_steps)]
            candidates += [s for s in reversed(pool) if s < step]
        errors = []
        for s in candidates:
            try:
                model = self._restore_step(s, load_updater, mesh=mesh,
                                           sharding_rules=sharding_rules)
            except Exception as e:  # noqa: BLE001 - orbax raises many kinds
                errors.append(f"step {s}: {type(e).__name__}: {e}")
                if not fallback:
                    raise ValueError(
                        f"checkpoint step {s} in {self.directory} is "
                        f"unrestorable (truncated or corrupt?): {e}") from e
                continue
            self.restored_step = s
            if errors:
                import logging
                logging.getLogger(__name__).warning(
                    "Restored checkpoint step %s after newer step(s) "
                    "failed integrity: %s", s, "; ".join(errors))
            return model
        raise ValueError(
            f"no restorable checkpoint in {self.directory}: "
            + "; ".join(errors))

    def _restore_step(self, step: int, load_updater: bool, *,
                      mesh=None, sharding_rules=None):
        import orbax.checkpoint as ocp
        model = _build_model(self.directory)
        template = _template_for(model, self._mgr.item_metadata(step))
        if mesh is not None:
            template = _sharded_template(model, template, mesh,
                                         sharding_rules)
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template))
        model = _apply_state(model, state, load_updater)
        if mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import (
                shard_model_with_rules)
            shard_model_with_rules(model, mesh, sharding_rules)
        return model

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "OrbaxCheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
