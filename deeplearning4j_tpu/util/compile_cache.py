"""Persistent XLA compilation cache — restarts serve hot from disk.

One call points JAX's compilation cache at a directory
(``jax_compilation_cache_dir``); every backend compile is then written
there keyed on the HLO hash, and an identical compile in a LATER process —
a serving restart, a version rollback re-warming the same architecture —
loads the executable from disk instead of recompiling. CPU, GPU and TPU
backends all support it on the pinned jax version (verified empirically:
cache files appear on the CPU mesh).

Two gotchas this module absorbs so callers can't hold it wrong:

- the thresholds: by default JAX only persists compiles that took >= 1s
  and are >= 64 KiB; a serving warmup full of small per-bucket forwards
  would persist NOTHING. We lower both floors to "everything".
- the latch: whether the cache is used is decided ONCE, at the first
  compile of the process. Setting the dir after anything compiled (the
  usual case — model loading compiles init fns) silently disables it, so
  we reset the decision after flipping the config.
"""

from __future__ import annotations

import os
from typing import Optional

_enabled_dir: Optional[str] = None


def enable_persistent_compile_cache(cache_dir: str) -> str:
    """Point the process's XLA compilation cache at ``cache_dir``
    (created if missing). Idempotent; returns the directory. Raises
    ``ValueError`` if a DIFFERENT directory is already active — the cache
    decision is process-wide and silently retargeting it would split
    warm state across two directories."""
    global _enabled_dir
    cache_dir = os.path.abspath(str(cache_dir))
    if _enabled_dir is not None:
        if _enabled_dir != cache_dir:
            raise ValueError(
                f"persistent compile cache already active at {_enabled_dir}"
                f"; cannot retarget to {cache_dir}")
        return cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for flag, value in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                        ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(flag, value)
        except Exception:  # noqa: BLE001 — flag renamed/absent on other jax
            pass
    try:
        # un-latch the per-process "is the cache used" decision (it is
        # taken at the FIRST compile, usually long before serving starts)
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private surface; best-effort
        pass
    _enabled_dir = cache_dir
    return cache_dir


def persistent_compile_cache_dir() -> Optional[str]:
    """The active cache directory, or None when not enabled."""
    return _enabled_dir
