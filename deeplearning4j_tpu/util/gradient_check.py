"""Gradient check harness — the correctness backbone.

Reference: ``deeplearning4j-nn/.../gradientcheck/GradientCheckUtil.java:109``
(central finite differences ``(C(w+ε)−C(w−ε))/2ε`` vs analytic backprop, max
relative error per parameter). Here "analytic" means ``jax.grad``; the check
still matters because layer forwards can silently break differentiability
assumptions (wrong masking, stop_gradients, non-smooth kinks at tested points).

Runs in float64 on CPU for epsilon stability (DL4J requires double precision
too, GradientCheckUtil doc ``:47``).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients_fn(loss_fn: Callable, params, *, epsilon: float = 1e-6,
                       max_rel_error: float = 1e-5, min_abs_error: float = 1e-8,
                       print_results: bool = False, subset: Optional[int] = None,
                       seed: int = 0) -> bool:
    """Check ``jax.grad(loss_fn)`` against central finite differences.

    loss_fn: params -> scalar. params: arbitrary pytree.
    subset: if set, check only this many randomly chosen coordinates per
    parameter (large layers would otherwise need millions of evals).
    """
    with jax.enable_x64(True):
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), params)
        loss_fn = jax.jit(loss_fn)  # compile once; FD loop then runs compiled
        analytic = jax.grad(loss_fn)(params64)

        leaves, treedef = jax.tree_util.tree_flatten(params64)
        grad_leaves = jax.tree_util.tree_leaves(analytic)
        rng = np.random.default_rng(seed)
        ok = True
        max_err_seen = 0.0
        for li, (leaf, g) in enumerate(zip(leaves, grad_leaves)):
            flat = np.array(leaf, np.float64).ravel()  # writable copy
            gflat = np.asarray(g, np.float64).ravel()
            n = flat.size
            idxs = (rng.choice(n, size=min(subset, n), replace=False)
                    if subset is not None and subset < n else range(n))
            for i in idxs:
                orig = flat[i]
                flat[i] = orig + epsilon
                lp = float(loss_fn(jax.tree_util.tree_unflatten(
                    treedef, _rebuild(leaves, li, flat))))
                flat[i] = orig - epsilon
                lm = float(loss_fn(jax.tree_util.tree_unflatten(
                    treedef, _rebuild(leaves, li, flat))))
                flat[i] = orig
                numeric = (lp - lm) / (2 * epsilon)
                a = gflat[i]
                abs_err = abs(a - numeric)
                denom = abs(a) + abs(numeric)
                rel = abs_err / denom if denom > 0 else 0.0
                max_err_seen = max(max_err_seen, rel if abs_err > min_abs_error else 0.0)
                if rel > max_rel_error and abs_err > min_abs_error:
                    ok = False
                    if print_results:
                        print(f"  FAIL leaf {li} idx {i}: analytic={a:.3e} "
                              f"numeric={numeric:.3e} rel={rel:.3e}")
        if print_results:
            print(f"gradient check {'PASSED' if ok else 'FAILED'}; "
                  f"max rel error (significant): {max_err_seen:.3e}")
        return ok


def _rebuild(leaves, li, flat):
    new = list(leaves)
    new[li] = jnp.asarray(flat.reshape(np.asarray(leaves[li]).shape), jnp.float64)
    return new


def check_model_gradients(model, x, y, *, features_mask=None, labels_mask=None,
                          epsilon: float = 1e-6,
                          max_rel_error: float = 1e-5, min_abs_error: float = 1e-8,
                          subset: Optional[int] = 64, seed: int = 0,
                          print_results: bool = False) -> bool:
    """GradientCheckUtil.checkGradients equivalent for a MultiLayerNetwork /
    ComputationGraph-style model exposing ``_loss_fn(params, states, ...)``."""
    with jax.enable_x64(True):
        x = jnp.asarray(np.asarray(x), jnp.float64)
        y = jnp.asarray(np.asarray(y), jnp.float64)
        fm = None if features_mask is None else jnp.asarray(np.asarray(features_mask), jnp.float64)
        lm = None if labels_mask is None else jnp.asarray(np.asarray(labels_mask), jnp.float64)
        states = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), model.states)

        def loss_fn(params):
            loss, _ = model._loss_fn(params, states, x, y, None, fm, lm, train=False)
            return loss

        return check_gradients_fn(loss_fn, model.params, epsilon=epsilon,
                                  max_rel_error=max_rel_error,
                                  min_abs_error=min_abs_error, subset=subset,
                                  seed=seed, print_results=print_results)


def check_graph_gradients(graph, features, labels, *, epsilon: float = 1e-6,
                          max_rel_error: float = 1e-5, min_abs_error: float = 1e-8,
                          subset: Optional[int] = 64, seed: int = 0,
                          print_results: bool = False) -> bool:
    """Gradient check for a ComputationGraph (multi-input/multi-output).

    Reference: ``GradientCheckUtil.checkGradients`` ComputationGraph overload.
    """
    if not isinstance(features, (list, tuple)):
        features = [features]
    if not isinstance(labels, (list, tuple)):
        labels = [labels]
    with jax.enable_x64(True):
        to64 = lambda a: jnp.asarray(np.asarray(a), jnp.float64)
        inputs = {n: to64(f) for n, f in zip(graph.conf.inputs, features)}
        labs = [to64(l) for l in labels]
        states = jax.tree_util.tree_map(to64, graph.states)

        def loss_fn(params):
            loss, _ = graph._loss_fn(params, states, inputs, labs, None, None,
                                     None, train=False)
            return loss

        return check_gradients_fn(loss_fn, graph.params, epsilon=epsilon,
                                  max_rel_error=max_rel_error,
                                  min_abs_error=min_abs_error, subset=subset,
                                  seed=seed, print_results=print_results)
