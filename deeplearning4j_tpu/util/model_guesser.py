"""Guess-and-load models/configs from an arbitrary file.

Parity with ``deeplearning4j-core/.../util/ModelGuesser.java``: try each
known loader in the reference's order until one succeeds —

``load_model_guess``: own MultiLayerNetwork zip → own ComputationGraph
zip → reference DL4J MLN zip → reference DL4J CG zip → Keras HDF5
(functional, then sequential).

``load_config_guess``: MultiLayerConfiguration JSON → Keras config
(sequential and functional share one entry point here) →
ComputationGraphConfiguration JSON → MLN YAML → CG YAML (JSON is tried
before YAML deliberately, as in the reference — YAML "accidentally"
parses JSON).
"""

from __future__ import annotations

from typing import Any, List, Tuple


class ModelGuesserException(Exception):
    """No known loader accepted the file."""


def _try_all(path: str, attempts: List[Tuple[str, Any]], kind: str):
    errors = []
    for name, fn in attempts:
        try:
            return fn(path)
        except Exception as e:  # noqa: BLE001 - each loader may fail its own way
            errors.append(f"{name}: {type(e).__name__}: {e}")
    detail = "; ".join(errors)
    raise ModelGuesserException(
        f"Unable to load {kind} from path {path} "
        f"(invalid file or not a known {kind} type). Tried: {detail}")


def load_model_guess(path: str):
    """Load a full model of unknown provenance (``loadModelGuess``)."""
    from deeplearning4j_tpu.util import model_serializer as ms
    from deeplearning4j_tpu.modelimport import dl4j
    from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport

    attempts = [
        ("own MultiLayerNetwork zip", ms.restore_multi_layer_network),
        ("own ComputationGraph zip", ms.restore_computation_graph),
        ("DL4J MultiLayerNetwork zip", dl4j.restore_multi_layer_network),
        ("DL4J ComputationGraph zip", dl4j.restore_computation_graph),
        ("Keras model h5", KerasModelImport.import_keras_model_and_weights),
        ("Keras sequential h5",
         KerasModelImport.import_keras_sequential_model_and_weights),
    ]
    return _try_all(path, attempts, "model")


def load_config_guess(path: str):
    """Load a network configuration of unknown provenance
    (``loadConfigGuess``)."""
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
    from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport

    def _read(p):
        with open(p, "r", encoding="utf-8") as fh:
            return fh.read()

    attempts = [
        ("MultiLayerConfiguration JSON",
         lambda p: MultiLayerConfiguration.from_json(_read(p))),
        # one Keras entry: import_keras_model_configuration dispatches
        # sequential vs functional internally
        ("Keras config",
         KerasModelImport.import_keras_model_configuration),
        ("ComputationGraphConfiguration JSON",
         lambda p: ComputationGraphConfiguration.from_json(_read(p))),
        ("MultiLayerConfiguration YAML",
         lambda p: MultiLayerConfiguration.from_yaml(_read(p))),
        ("ComputationGraphConfiguration YAML",
         lambda p: ComputationGraphConfiguration.from_yaml(_read(p))),
    ]
    return _try_all(path, attempts, "configuration")


def load_normalizer(path: str):
    """Facade for ``ModelSerializer.restoreNormalizerFromFile``
    (``ModelGuesser.java:38``): our own ``normalizer.json`` zips first,
    then the reference's binary ``normalizer.bin``
    (NormalizerSerializer stream)."""
    from deeplearning4j_tpu.util import model_serializer as ms
    own = ms.restore_normalizer(path)
    if own is not None:
        return own
    from deeplearning4j_tpu.modelimport import dl4j
    return dl4j.restore_normalizer(path)
