"""Small filesystem primitives shared across subsystems."""

from __future__ import annotations

import os


def atomic_write_text(path: str, text: str, *, fsync: bool = False) -> None:
    """Write ``text`` to ``path`` atomically (tmp file + ``os.replace``).

    ``fsync=True`` additionally flushes the tmp file to disk before the
    replace — the journaling callers (pipeline state) pay it; the
    high-frequency callers (elastic heartbeats/stamps) do not.
    """
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        if fsync:
            fh.flush()
            os.fsync(fh.fileno())
    os.replace(tmp, path)
