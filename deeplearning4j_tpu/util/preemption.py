"""Preemption handling: checkpoint-on-signal + resume.

The reference has **no** elastic/preemption story (SURVEY.md §5: worker
membership fixed at job start, fault tolerance delegated to Spark retry; the
survey explicitly calls for real preemption handling in the TPU build). TPU
VMs receive maintenance-event preemptions as SIGTERM with a grace window —
this module arms a handler that snapshots the model (params + updater state
+ training position) via ModelSerializer and lets training resume from the
snapshot after rescheduling.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
from typing import Callable, Optional

log = logging.getLogger(__name__)


class PreemptionHandler:
    """Arms SIGTERM (and optionally SIGINT) to checkpoint a model.

    Usage::

        handler = PreemptionHandler(net, "ckpt/preempt.zip")
        handler.arm()
        net.fit(iterator, epochs=...)   # a SIGTERM mid-fit saves + raises
        handler.disarm()

    The saved zip is a normal ModelSerializer checkpoint plus a sidecar
    ``.state.json`` recording iteration/epoch, so ``resume()`` restores the
    exact training position.
    """

    def __init__(self, model, checkpoint_path: str,
                 signals=(signal.SIGTERM,), exit_after_save: bool = False,
                 on_preempt: Optional[Callable] = None,
                 backend: str = "zip",
                 async_saver=None, flush_grace_s: float = 30.0):
        if backend not in ("zip", "orbax"):
            raise ValueError("backend must be 'zip' or 'orbax'")
        self.model = model
        self.checkpoint_path = str(checkpoint_path)
        self.signals = tuple(signals)
        self.exit_after_save = exit_after_save
        self.on_preempt = on_preempt
        self.backend = backend
        #: anything with ``flush(timeout) -> bool`` (e.g. an elastic
        #: AsyncCheckpointSession): an in-flight ASYNC checkpoint is
        #: flushed inside the SIGTERM grace window (after this handler's
        #: own immediate snapshot) — otherwise the preemption abandons a
        #: torn step that was seconds from committing
        self.async_saver = async_saver
        self.flush_grace_s = flush_grace_s
        self.flush_timed_out = threading.Event()
        self._previous = {}
        self.preempted = threading.Event()
        self.saved = threading.Event()
        self._hook = None

    # -- checkpointing ---------------------------------------------------
    def save(self) -> str:
        if self.backend == "orbax":
            # step-rotated saves (max_to_keep=2): a plain overwrite would
            # delete the previous good checkpoint BEFORE the new one
            # commits (orbax force=True rmtree), so a grace window
            # expiring mid-write would lose both. With rotation the old
            # step survives until the new step finalizes.
            from deeplearning4j_tpu.util.orbax_checkpoint import (
                OrbaxCheckpointManager,
            )
            if getattr(self, "_orbax_mgr", None) is None:
                self._orbax_mgr = OrbaxCheckpointManager(
                    self.checkpoint_path, max_to_keep=2)
            step = (self._orbax_mgr.latest_step() or 0) + 1
            self._orbax_mgr.save(step, self.model)
            self._orbax_mgr.wait_until_finished()
            self.saved.set()
            return self.checkpoint_path
        import zipfile

        from deeplearning4j_tpu.util import model_serializer

        directory = os.path.dirname(self.checkpoint_path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        tmp = self.checkpoint_path + ".tmp"
        model_serializer.write_model(self.model, tmp)
        # training position travels INSIDE the zip so the whole checkpoint
        # is one atomic os.replace — no torn sidecar in the grace window
        with zipfile.ZipFile(tmp, "a") as z:
            z.writestr("preemption_state.json", json.dumps(
                {"iteration": getattr(self.model, "iteration", 0),
                 "epoch": getattr(self.model, "epoch", 0)}))
        os.replace(tmp, self.checkpoint_path)
        self.saved.set()
        return self.checkpoint_path

    @staticmethod
    def resume(checkpoint_path: str):
        """(model, state_dict) from a preemption checkpoint — a zip file
        or an orbax directory, detected from what is on disk. Orbax
        directories restore the latest COMMITTED step (a save torn by the
        grace window falls back to the preceding one)."""
        import zipfile

        if os.path.isdir(str(checkpoint_path)):
            from deeplearning4j_tpu.util.orbax_checkpoint import (
                OrbaxCheckpointManager,
                restore_model,
            )
            with OrbaxCheckpointManager(str(checkpoint_path)) as mgr:
                if mgr.latest_step() is not None:
                    model = mgr.restore()
                else:  # plain save_model layout (no step dirs)
                    model = restore_model(str(checkpoint_path))
            return model, {"iteration": model.iteration,
                           "epoch": model.epoch}

        from deeplearning4j_tpu.util import model_serializer

        model = model_serializer.restore_model(str(checkpoint_path))
        state = {"iteration": 0, "epoch": 0}
        with zipfile.ZipFile(str(checkpoint_path)) as z:
            if "preemption_state.json" in z.namelist():
                state = json.loads(z.read("preemption_state.json"))
        model.iteration = int(state.get("iteration", 0))
        model.epoch = int(state.get("epoch", 0))
        return model, state

    def rollback(self):
        """Restore the last good checkpoint this handler wrote — the
        watchdog-recovery flow: a raise-policy ``TrainingWatchdog``
        (``observe/health.py``) aborts a diverging ``fit()`` with
        ``WatchdogAlarm``, the caller catches it and rolls the model back
        to the pre-divergence snapshot. Returns ``(model, state)`` like
        :meth:`resume`.

        Strict about provenance: only a checkpoint THIS handler wrote
        qualifies — a file left at the same path by an earlier process is
        not a known-good snapshot of the current run (restore those
        explicitly with :meth:`resume`)."""
        if not self.saved.is_set():
            raise RuntimeError(
                f"this handler has not written a checkpoint to "
                f"{self.checkpoint_path}; rollback() only restores its own "
                f"snapshot — use resume() for a pre-existing file")
        return self.resume(self.checkpoint_path)

    def flush_async(self) -> bool:
        """Drain an in-flight async checkpoint under the bounded grace
        deadline (``flush_grace_s``). True when everything landed; on
        timeout the in-flight step stays torn (unstamped — never
        restorable, by the commit protocol) and ``flush_timed_out`` is
        set. The SIGTERM handler calls this AFTER taking its own
        snapshot — a hung flush must not burn the grace window before
        anything at all is saved."""
        if self.async_saver is None:
            return True
        ok = bool(self.async_saver.flush(timeout=self.flush_grace_s))
        if not ok:
            self.flush_timed_out.set()
            log.warning(
                "In-flight async checkpoint did not land within the "
                "%.1fs grace window; the torn step is unstamped and "
                "will never be restored", self.flush_grace_s)
        return ok

    # -- signal plumbing -------------------------------------------------
    def _handle(self, signum, frame):
        log.warning("Preemption signal %s: checkpointing to %s",
                    signum, self.checkpoint_path)
        self.preempted.set()
        try:
            self.save()
        except RuntimeError as e:
            # the signal landed inside a donating train step: params are
            # transiently invalid ("Array has been deleted"). Defer — the
            # armed listener (or the caller via maybe_save_pending) saves at
            # the next step boundary.
            log.warning("Deferring preemption checkpoint to the next step "
                        "boundary (%s)", e)
        # own snapshot FIRST (fast, and safe even if the filesystem that
        # stalled the async save is the slow one), THEN spend what is
        # left of the grace window letting the overlapped save commit —
        # the reverse order could burn the whole window on a hung flush
        # and lose both checkpoints
        self.flush_async()
        if self.on_preempt is not None:
            self.on_preempt(self)
        if self.exit_after_save and self.saved.is_set():
            raise SystemExit(143)

    def maybe_save_pending(self) -> bool:
        """Complete a deferred preemption save; call at a step boundary."""
        if self.preempted.is_set() and not self.saved.is_set():
            self.save()
            if self.exit_after_save:
                raise SystemExit(143)
            return True
        return False

    def arm(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            # signal.signal would raise a bare ValueError("signal only
            # works in main thread") — say what to do instead
            raise RuntimeError(
                "PreemptionHandler.arm() must be called from the main "
                "thread: CPython only delivers signal handlers there. "
                "From a worker/background thread, either arm the handler "
                "on the main thread before spawning, or supervise the "
                "training process externally with ElasticJobSupervisor "
                "(deeplearning4j_tpu.parallel.elastic), which handles "
                "SIGKILL-style death no in-process handler can see")
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handle)
        # safe-point hook: complete deferred saves between training steps
        listeners = getattr(self.model, "listeners", None)
        if listeners is not None and self._hook is None:
            handler = self

            class _Hook:
                def iteration_done(self, model, iteration, epoch):
                    handler.maybe_save_pending()

            self._hook = _Hook()
            listeners.append(self._hook)
        return self

    def disarm(self) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
        listeners = getattr(self.model, "listeners", None)
        if listeners is not None and self._hook in listeners:
            listeners.remove(self._hook)
        self._hook = None

    def __enter__(self) -> "PreemptionHandler":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.disarm()
