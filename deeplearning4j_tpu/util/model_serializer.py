"""ModelSerializer — checkpoint save/restore.

Reference: ``util/ModelSerializer.java``: a zip holding JSON config +
flattened params + updater state (+ optional normalizer) with
``writeModel:51``, ``restoreMultiLayerNetwork:182``,
``restoreComputationGraph:389``, ``addNormalizerToModel:654``.

Format here: a zip with
- ``configuration.json``  — the network config (self-describing: sequential
  vs graph via its ``format`` field)
- ``params.npz``          — param arrays named ``<layer>/<param>``
- ``updater.npz``         — updater state ``<layer>/<param>/<slot>`` (optional)
- ``states.npz``          — layer runtime state (BN running stats) (optional)
- ``normalizer.json``     — fitted normalizer (optional)
- ``meta.json``           — iteration/epoch counters

Arrays are saved in the model's dtype; restore places them back on the
default device (re-shard with ``parallel.shard_model`` afterwards for
distributed resume).
"""

from __future__ import annotations

import io
import json
import zipfile
import zlib
from pathlib import Path
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

CONFIG_NAME = "configuration.json"
PARAMS_NAME = "params.npz"
UPDATER_NAME = "updater.npz"
STATES_NAME = "states.npz"
NORMALIZER_NAME = "normalizer.json"
META_NAME = "meta.json"


def _layer_keys(model):
    """(key, params_dict) pairs — list-indexed for MLN, name-keyed for graphs."""
    if isinstance(model.params, dict):
        return list(model.params.items())
    return [(str(i), p) for i, p in enumerate(model.params)]


def _npz_bytes(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def replace_zip_entry(path, entry_name: str, payload: bytes) -> None:
    """Atomically rewrite the zip at ``path`` with every entry except
    ``entry_name`` (matched case-insensitively, as ``ModelSerializer.java:
    670`` does), then append ``payload`` under that name. Preserves the
    original file's permissions and cleans up the temp file on error."""
    import os
    import tempfile

    path = str(path)
    mode = os.stat(path).st_mode & 0o7777
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)),
                               suffix=".zip")
    os.close(fd)
    try:
        with zipfile.ZipFile(path) as zin, \
                zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zout:
            for entry in zin.namelist():
                if entry.lower() == entry_name.lower():
                    continue
                zout.writestr(entry, zin.read(entry))
            zout.writestr(entry_name, payload)
        os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def write_model(model, path: Union[str, Path], *, save_updater: bool = True,
                normalizer=None) -> None:
    """ModelSerializer.writeModel parity."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)

    params = {f"{k}/{n}": np.asarray(v)
              for k, pd in _layer_keys(model) for n, v in pd.items()}

    upd = {}
    if save_updater and model.updater_states is not None:
        us = model.updater_states
        items = us.items() if isinstance(us, dict) else ((str(i), u) for i, u in enumerate(us))
        for k, per_param in items:
            for pn, slots in per_param.items():
                for sn, v in slots.items():
                    upd[f"{k}/{pn}/{sn}"] = np.asarray(v)

    states = {}
    st = model.states
    if st is not None:
        items = st.items() if isinstance(st, dict) else ((str(i), s) for i, s in enumerate(st))
        for k, sd in items:
            for n, v in (sd or {}).items():
                states[f"{k}/{n}"] = np.asarray(v)

    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(CONFIG_NAME, model.conf.to_json())
        z.writestr(PARAMS_NAME, _npz_bytes(params))
        if upd:
            z.writestr(UPDATER_NAME, _npz_bytes(upd))
        if states:
            z.writestr(STATES_NAME, _npz_bytes(states))
        if normalizer is not None:
            z.writestr(NORMALIZER_NAME, normalizer.to_json())
        from deeplearning4j_tpu.nn.layers.attention import QKV_LAYOUT
        z.writestr(META_NAME, json.dumps(
            {"iteration": model.iteration, "epoch": model.epoch,
             "framework": "deeplearning4j_tpu",
             # round-5 layout stamp: fused attention columns are head-major
             "qkv_layout": QKV_LAYOUT}))


def _load_npz(z: zipfile.ZipFile, name: str) -> Optional[dict]:
    if name not in z.namelist():
        return None
    with z.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        return {k: data[k] for k in data.files}


def validate_model_zip(path: Union[str, Path]) -> list:
    """Integrity check for a zip checkpoint; returns a list of problems
    (empty = valid). Catches the torn-write failure modes a preemption
    (or the fault injector's ``corrupt_checkpoint``) produces: not a zip
    at all, truncated central directory, CRC damage in a required member,
    or required members missing entirely."""
    problems = []
    try:
        with zipfile.ZipFile(path, "r") as z:
            for required in (CONFIG_NAME, PARAMS_NAME):
                if required not in z.namelist():
                    problems.append(f"missing required entry {required!r}")
            try:
                bad = z.testzip()
            except Exception as e:  # noqa: BLE001 - zlib.error, EOFError...
                # testzip only RETURNS names for CRC mismatches; damage to
                # the compressed stream itself raises from the inflater
                bad, problems = None, problems + [f"undecodable entry: {e}"]
            if bad is not None:
                problems.append(f"CRC mismatch in entry {bad!r}")
    except (zipfile.BadZipFile, OSError) as e:
        problems.append(f"unreadable zip: {e}")
    return problems


def _restore(path: Union[str, Path], *, load_updater: bool = True):
    from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    try:
        # zipfile verifies each member's CRC as it is read, so damage
        # surfaces here for free — the full validate_model_zip scan
        # (a second decompression of every member) runs only on the
        # failure path, to name the problem in the error
        with zipfile.ZipFile(path, "r") as z:
            conf_d = json.loads(z.read(CONFIG_NAME))
            params = _load_npz(z, PARAMS_NAME)
            upd = _load_npz(z, UPDATER_NAME) if load_updater else None
            states = _load_npz(z, STATES_NAME)
            meta = json.loads(z.read(META_NAME)) \
                if META_NAME in z.namelist() else {}
    except (zipfile.BadZipFile, zlib.error, EOFError, KeyError,
            OSError) as e:
        problems = validate_model_zip(path)
        raise ValueError(
            f"checkpoint {path} failed integrity validation: "
            + ("; ".join(problems) if problems else str(e))
            + " — the file is truncated/corrupt or not a model zip") from e

    is_graph = "ComputationGraph" in conf_d.get("format", "")
    if is_graph:
        conf = ComputationGraphConfiguration.from_dict(conf_d)
        model = ComputationGraph(conf)
    else:
        conf = MultiLayerConfiguration.from_dict(conf_d)
        model = MultiLayerNetwork(conf)
    model.init()

    def put(container, key, pn, arr):
        tgt = container[key] if isinstance(container, dict) else container[int(key)]
        if pn in tgt and tuple(tgt[pn].shape) != tuple(arr.shape):
            raise ValueError(
                f"checkpoint {path}: array {key}/{pn} has shape "
                f"{tuple(arr.shape)} but the configuration allocates "
                f"{tuple(tgt[pn].shape)} — checkpoint and config disagree "
                f"(wrong file, or corrupt)")
        tgt[pn] = jnp.asarray(arr)

    for full, arr in params.items():
        key, pn = full.split("/", 1)
        put(model.params, key, pn, arr)
    if states:
        for full, arr in states.items():
            key, pn = full.split("/", 1)
            put(model.states, key, pn, arr)
    if upd:
        for full, arr in upd.items():
            key, pn, sn = full.split("/", 2)
            tgt = (model.updater_states[key] if isinstance(model.updater_states, dict)
                   else model.updater_states[int(key)])
            tgt[pn][sn] = jnp.asarray(arr)
    model.iteration = int(meta.get("iteration", 0))
    model.epoch = int(meta.get("epoch", 0))
    from deeplearning4j_tpu.nn.layers.attention import (QKV_LAYOUT,
                                                        repack_legacy_fused_qkv)
    if meta.get("qkv_layout") != QKV_LAYOUT:
        # pre-round-5 checkpoint: fused attention weights were saved in the
        # [3,H,Dh] block-major column order — repack to head-major
        repack_legacy_fused_qkv(model)
    return model


def restore_multi_layer_network(path, *, load_updater: bool = True):
    """ModelSerializer.restoreMultiLayerNetwork:182 parity."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    m = _restore(path, load_updater=load_updater)
    if not isinstance(m, MultiLayerNetwork):
        raise ValueError(f"{path} holds a ComputationGraph, not a MultiLayerNetwork")
    return m


def restore_computation_graph(path, *, load_updater: bool = True):
    """ModelSerializer.restoreComputationGraph:389 parity."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    m = _restore(path, load_updater=load_updater)
    if not isinstance(m, ComputationGraph):
        raise ValueError(f"{path} holds a MultiLayerNetwork, not a ComputationGraph")
    return m


def restore_model(path, *, load_updater: bool = True):
    """Type-agnostic restore."""
    return _restore(path, load_updater=load_updater)


def add_normalizer_to_model(path, normalizer) -> None:
    """ModelSerializer.addNormalizerToModel:654 parity (rewrites the zip)."""
    replace_zip_entry(path, NORMALIZER_NAME,
                      normalizer.to_json().encode("utf-8"))


def restore_normalizer(path):
    from deeplearning4j_tpu.datasets.normalizers import Normalizer
    with zipfile.ZipFile(path, "r") as z:
        if NORMALIZER_NAME not in z.namelist():
            return None
        return Normalizer.from_json(z.read(NORMALIZER_NAME).decode())
