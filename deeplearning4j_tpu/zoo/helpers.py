"""Reusable block builders for zoo architectures.

Reference: ``deeplearning4j-zoo/.../zoo/model/helper/DarknetHelper.java``,
``FaceNetHelper.java``, ``InceptionResNetHelper.java`` and the private
``convBlock``/``identityBlock`` methods in ``ResNet50.java:89-167``. Each
helper appends named vertices to a :class:`GraphBuilder` and returns the name
of the block's output vertex, so architectures compose as plain function
calls over the DAG builder.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.vertices import ElementWiseVertex, MergeVertex, ScaleVertex


def conv_bn_act(g: GraphBuilder, name: str, inp: str, n_out: int,
                kernel: Tuple[int, int] = (3, 3), stride: Tuple[int, int] = (1, 1),
                mode: str = "same", activation: str = "relu",
                eps: float = 1e-5, decay: float = 0.9) -> str:
    """conv → batchnorm → activation, the universal CNN building block."""
    g.add_layer(name + "_conv",
                ConvolutionLayer(n_out=n_out, kernel_size=kernel, stride=stride,
                                 convolution_mode=mode, activation="identity",
                                 has_bias=False),
                inp)
    g.add_layer(name + "_bn", BatchNormalizationLayer(eps=eps, decay=decay,
                                                      activation="identity"),
                name + "_conv")
    g.add_layer(name + "_act", ActivationLayer(activation=activation), name + "_bn")
    return name + "_act"


def darknet_block(g: GraphBuilder, num: int, inp: str, n_out: int,
                  filter_size: int = 3, pool: int = 0, pool_stride: int = 0) -> str:
    """Darknet conv unit: conv(same, no bias) → BN → leakyrelu(0.1) [→ maxpool].

    Reference: ``DarknetHelper.addLayers`` (conv + BN + LeakyReLU + optional
    2x2 maxpool).
    """
    name = f"convolution2d_{num}"
    g.add_layer(name,
                ConvolutionLayer(n_out=n_out, kernel_size=(filter_size, filter_size),
                                 stride=(1, 1), convolution_mode="same",
                                 activation="identity", has_bias=False),
                inp)
    g.add_layer(f"batchnormalization_{num}",
                BatchNormalizationLayer(activation="identity"), name)
    g.add_layer(f"activation_{num}", ActivationLayer(activation="leakyrelu"),
                f"batchnormalization_{num}")
    out = f"activation_{num}"
    if pool:
        ps = pool_stride or pool
        g.add_layer(f"maxpooling2d_{num}",
                    SubsamplingLayer(pooling_type="max", kernel_size=(pool, pool),
                                     stride=(ps, ps),
                                     convolution_mode="same" if ps == 1 else "truncate"),
                    out)
        out = f"maxpooling2d_{num}"
    return out


def resnet_identity_block(g: GraphBuilder, kernel: Tuple[int, int],
                          filters: Sequence[int], stage: str, block: str,
                          inp: str) -> str:
    """Bottleneck residual block without projection (``ResNet50.java:89``)."""
    f1, f2, f3 = filters
    cn, bn, an = (f"res{stage}{block}_branch", f"bn{stage}{block}_branch",
                  f"act{stage}{block}_branch")
    # every conv here feeds a BatchNorm, so conv bias is mathematically
    # redundant (BN's mean subtraction cancels it, beta replaces it) — the
    # canonical He et al. layout; dropping it also removes a full
    # backward-pass reduction over every dy tensor (measured 18% of the
    # ResNet50 train step on v5e)
    g.add_layer(cn + "2a", ConvolutionLayer(n_out=f1, kernel_size=(1, 1),
                                            activation="identity",
                                            has_bias=False), inp)
    g.add_layer(bn + "2a", BatchNormalizationLayer(activation="identity"), cn + "2a")
    g.add_layer(an + "2a", ActivationLayer(activation="relu"), bn + "2a")
    g.add_layer(cn + "2b", ConvolutionLayer(n_out=f2, kernel_size=kernel,
                                            convolution_mode="same",
                                            activation="identity",
                                            has_bias=False), an + "2a")
    g.add_layer(bn + "2b", BatchNormalizationLayer(activation="identity"), cn + "2b")
    g.add_layer(an + "2b", ActivationLayer(activation="relu"), bn + "2b")
    g.add_layer(cn + "2c", ConvolutionLayer(n_out=f3, kernel_size=(1, 1),
                                            activation="identity",
                                            has_bias=False), an + "2b")
    g.add_layer(bn + "2c", BatchNormalizationLayer(activation="identity"), cn + "2c")
    g.add_vertex(f"short{stage}{block}_branch", ElementWiseVertex(op="add"),
                 bn + "2c", inp)
    g.add_layer(cn, ActivationLayer(activation="relu"), f"short{stage}{block}_branch")
    return cn


def resnet_conv_block(g: GraphBuilder, kernel: Tuple[int, int],
                      filters: Sequence[int], stage: str, block: str, inp: str,
                      stride: Tuple[int, int] = (2, 2)) -> str:
    """Bottleneck residual block with strided projection shortcut
    (``ResNet50.java:125-167``)."""
    f1, f2, f3 = filters
    cn, bn, an = (f"res{stage}{block}_branch", f"bn{stage}{block}_branch",
                  f"act{stage}{block}_branch")
    # conv biases dropped: every conv feeds a BatchNorm (see identity block)
    g.add_layer(cn + "2a", ConvolutionLayer(n_out=f1, kernel_size=(1, 1),
                                            stride=stride, activation="identity",
                                            has_bias=False), inp)
    g.add_layer(bn + "2a", BatchNormalizationLayer(activation="identity"), cn + "2a")
    g.add_layer(an + "2a", ActivationLayer(activation="relu"), bn + "2a")
    g.add_layer(cn + "2b", ConvolutionLayer(n_out=f2, kernel_size=kernel,
                                            convolution_mode="same",
                                            activation="identity",
                                            has_bias=False), an + "2a")
    g.add_layer(bn + "2b", BatchNormalizationLayer(activation="identity"), cn + "2b")
    g.add_layer(an + "2b", ActivationLayer(activation="relu"), bn + "2b")
    g.add_layer(cn + "2c", ConvolutionLayer(n_out=f3, kernel_size=(1, 1),
                                            activation="identity",
                                            has_bias=False), an + "2b")
    g.add_layer(bn + "2c", BatchNormalizationLayer(activation="identity"), cn + "2c")
    # projection shortcut
    g.add_layer(cn + "1", ConvolutionLayer(n_out=f3, kernel_size=(1, 1),
                                           stride=stride, activation="identity",
                                           has_bias=False), inp)
    g.add_layer(bn + "1", BatchNormalizationLayer(activation="identity"), cn + "1")
    g.add_vertex(f"short{stage}{block}_branch", ElementWiseVertex(op="add"),
                 bn + "2c", bn + "1")
    g.add_layer(cn, ActivationLayer(activation="relu"), f"short{stage}{block}_branch")
    return cn


def inception_module(g: GraphBuilder, name: str, inp: str,
                     b1: int, b3r: int, b3: int, b5r: int, b5: int, pp: int) -> str:
    """GoogLeNet inception module (Szegedy 2014): four merged branches —
    1x1, 1x1→3x3, 1x1→5x5, maxpool→1x1. Reference: ``GoogLeNet.java``
    ``inception(...)`` helper."""
    g.add_layer(f"{name}-1x1", ConvolutionLayer(n_out=b1, kernel_size=(1, 1),
                                                activation="relu"), inp)
    g.add_layer(f"{name}-3x3reduce", ConvolutionLayer(n_out=b3r, kernel_size=(1, 1),
                                                      activation="relu"), inp)
    g.add_layer(f"{name}-3x3", ConvolutionLayer(n_out=b3, kernel_size=(3, 3),
                                                convolution_mode="same",
                                                activation="relu"), f"{name}-3x3reduce")
    g.add_layer(f"{name}-5x5reduce", ConvolutionLayer(n_out=b5r, kernel_size=(1, 1),
                                                      activation="relu"), inp)
    g.add_layer(f"{name}-5x5", ConvolutionLayer(n_out=b5, kernel_size=(5, 5),
                                                convolution_mode="same",
                                                activation="relu"), f"{name}-5x5reduce")
    g.add_layer(f"{name}-maxpool", SubsamplingLayer(pooling_type="max",
                                                    kernel_size=(3, 3), stride=(1, 1),
                                                    convolution_mode="same"), inp)
    g.add_layer(f"{name}-poolproj", ConvolutionLayer(n_out=pp, kernel_size=(1, 1),
                                                     activation="relu"), f"{name}-maxpool")
    g.add_vertex(name, MergeVertex(), f"{name}-1x1", f"{name}-3x3",
                 f"{name}-5x5", f"{name}-poolproj")
    return name


def inception_resnet_block_a(g: GraphBuilder, name: str, inp: str, scale: float) -> str:
    """Inception-ResNet-v1 block35 (``InceptionResNetHelper.inceptionV1ResA``):
    three merged branches → 1x1 projection, scaled residual add, relu."""
    b1 = conv_bn_act(g, f"{name}-b1", inp, 32, (1, 1))
    b2a = conv_bn_act(g, f"{name}-b2a", inp, 32, (1, 1))
    b2 = conv_bn_act(g, f"{name}-b2b", b2a, 32, (3, 3))
    b3a = conv_bn_act(g, f"{name}-b3a", inp, 32, (1, 1))
    b3b = conv_bn_act(g, f"{name}-b3b", b3a, 32, (3, 3))
    b3 = conv_bn_act(g, f"{name}-b3c", b3b, 32, (3, 3))
    g.add_vertex(f"{name}-merge", MergeVertex(), b1, b2, b3)
    g.add_layer(f"{name}-proj", ConvolutionLayer(n_out=256, kernel_size=(1, 1),
                                                 activation="identity"),
                f"{name}-merge")
    g.add_vertex(f"{name}-scale", ScaleVertex(scale_factor=scale), f"{name}-proj")
    g.add_vertex(f"{name}-residual", ElementWiseVertex(op="add"), inp, f"{name}-scale")
    g.add_layer(name, ActivationLayer(activation="relu"), f"{name}-residual")
    return name


def inception_resnet_block_b(g: GraphBuilder, name: str, inp: str, scale: float) -> str:
    """Inception-ResNet-v1 block17 (1x7/7x1 factorized branch)."""
    b1 = conv_bn_act(g, f"{name}-b1", inp, 128, (1, 1))
    b2a = conv_bn_act(g, f"{name}-b2a", inp, 128, (1, 1))
    b2b = conv_bn_act(g, f"{name}-b2b", b2a, 128, (1, 7))
    b2 = conv_bn_act(g, f"{name}-b2c", b2b, 128, (7, 1))
    g.add_vertex(f"{name}-merge", MergeVertex(), b1, b2)
    g.add_layer(f"{name}-proj", ConvolutionLayer(n_out=896, kernel_size=(1, 1),
                                                 activation="identity"),
                f"{name}-merge")
    g.add_vertex(f"{name}-scale", ScaleVertex(scale_factor=scale), f"{name}-proj")
    g.add_vertex(f"{name}-residual", ElementWiseVertex(op="add"), inp, f"{name}-scale")
    g.add_layer(name, ActivationLayer(activation="relu"), f"{name}-residual")
    return name


def inception_resnet_block_c(g: GraphBuilder, name: str, inp: str, scale: float) -> str:
    """Inception-ResNet-v1 block8 (1x3/3x1 factorized branch)."""
    b1 = conv_bn_act(g, f"{name}-b1", inp, 192, (1, 1))
    b2a = conv_bn_act(g, f"{name}-b2a", inp, 192, (1, 1))
    b2b = conv_bn_act(g, f"{name}-b2b", b2a, 192, (1, 3))
    b2 = conv_bn_act(g, f"{name}-b2c", b2b, 192, (3, 1))
    g.add_vertex(f"{name}-merge", MergeVertex(), b1, b2)
    g.add_layer(f"{name}-proj", ConvolutionLayer(n_out=1792, kernel_size=(1, 1),
                                                 activation="identity"),
                f"{name}-merge")
    g.add_vertex(f"{name}-scale", ScaleVertex(scale_factor=scale), f"{name}-proj")
    g.add_vertex(f"{name}-residual", ElementWiseVertex(op="add"), inp, f"{name}-scale")
    g.add_layer(name, ActivationLayer(activation="relu"), f"{name}-residual")
    return name
