"""Model zoo base machinery.

Reference: ``deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/ZooModel.java:51-69``
(pretrained download + checksum + init), ``ModelMetaData.java``, ``ZooType.java``,
``ModelSelector.java``. TPU-native differences: models build straight onto the
functional `MultiLayerNetwork`/`ComputationGraph` configs; pretrained weights
load from a local checkpoint path instead of an HTTP blob store (this image has
no egress), via :mod:`deeplearning4j_tpu.util.model_serializer`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple, Type


@dataclasses.dataclass(frozen=True)
class ModelMetaData:
    """Shape metadata (reference ``ZooModel.metaData()``)."""

    input_shape: Tuple[Tuple[int, ...], ...]  # per graph input, CHW order like DL4J
    n_outputs: int = 1
    network_type: str = "cnn"  # "cnn" | "rnn"

    @property
    def use_mds(self) -> bool:
        return len(self.input_shape) > 1 or self.n_outputs > 1


class PretrainedType:
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


class ZooModel:
    """Base class for zoo architectures (``ZooModel.java``).

    Subclasses implement ``conf()`` (a MultiLayerConfiguration or
    ComputationGraphConfiguration) and ``meta_data()``; ``init()`` builds and
    initializes the runtime network.
    """

    def __init__(self, num_labels: int = 1000, seed: int = 123):
        self.num_labels = num_labels
        self.seed = seed

    # -- to implement ------------------------------------------------------
    def conf(self):
        raise NotImplementedError

    def meta_data(self) -> ModelMetaData:
        raise NotImplementedError

    # -- common ------------------------------------------------------------
    def init(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        c = self.conf()
        if isinstance(c, ComputationGraphConfiguration):
            return ComputationGraph(c).init(seed=self.seed)
        return MultiLayerNetwork(c).init(seed=self.seed)

    def _artifact_name(self, pretrained_type: str) -> str:
        """Cache-slot file name; models whose artifact varies beyond
        (class, type) — e.g. Darknet19's resolution-dependent weights —
        must extend this so distinct artifacts get distinct slots."""
        return f"{type(self).__name__.lower()}_{pretrained_type}.zip"

    def _cache_path(self, pretrained_type: str) -> str:
        root = os.environ.get("DL4J_TPU_ZOO_DIR",
                              os.path.expanduser("~/.deeplearning4j_tpu/zoo"))
        return os.path.join(root, self._artifact_name(pretrained_type))

    def pretrained_checkpoint(self, pretrained_type: str = PretrainedType.IMAGENET) -> Optional[str]:
        """Local cache path to pretrained weights, or None if absent.

        The reference's cache is ``~/.deeplearning4j/models`` filled by its
        downloader (``ZooModel.java:51-69``); ours is
        ``$DL4J_TPU_ZOO_DIR/<model>_<type>.zip``, filled either by the user
        or by :meth:`init_pretrained` fetching a registered URL.
        """
        p = self._cache_path(pretrained_type)
        return p if os.path.exists(p) else None

    #: subclasses/users may register weight-artifact URLs per pretrained
    #: type (``ZooModel.pretrainedUrl``; the reference points these at
    #: ``blob.deeplearning4j.org``). ``file://`` URLs work identically —
    #: the transport below is scheme-agnostic urllib.
    PRETRAINED_URLS: Dict[str, str] = {}

    def pretrained_url(self, pretrained_type: str) -> Optional[str]:
        return self.PRETRAINED_URLS.get(pretrained_type)

    #: subclasses/users may register expected Adler32 checksums per
    #: pretrained type (``ZooModel.pretrainedChecksum``; 0 = don't verify).
    #: NOTE the integrity limitation inherited from the reference: its blob
    #: store is plain http and Adler32 is not cryptographic, so this check
    #: catches corruption, not tampering. Register a SHA-256 in
    #: :attr:`PRETRAINED_SHA256` for tamper-evident verification.
    PRETRAINED_CHECKSUMS: Dict[str, int] = {}

    def pretrained_checksum(self, pretrained_type: str) -> int:
        return int(self.PRETRAINED_CHECKSUMS.get(pretrained_type, 0))

    #: optional cryptographic digests per pretrained type (hex SHA-256;
    #: beyond the reference, which verifies Adler32 only). Verified under
    #: the same provenance rule as the Adler32 registry.
    PRETRAINED_SHA256: Dict[str, str] = {}

    def pretrained_sha256(self, pretrained_type: str) -> str:
        return str(self.PRETRAINED_SHA256.get(pretrained_type, ""))

    def init_pretrained(self, pretrained_type: str = PretrainedType.IMAGENET,
                        expected_checksum: Optional[int] = None):
        """Build this architecture carrying pretrained weights
        (``ZooModel.initPretrained``, ``ZooModel.java:51-93``): resolve the
        cached artifact, verify its Adler32 checksum when one is expected,
        then restore through the FULL checkpoint reader — both this
        framework's own zips and the reference's DL4J ModelSerializer zips
        (``coefficients.bin`` + ``updaterState.bin``) load, for
        MultiLayerNetwork and ComputationGraph alike.

        A cache miss with a registered URL (:attr:`PRETRAINED_URLS`)
        triggers a fetch into the cache first — ``file://`` URLs exercise
        the identical transport/cache/checksum path as HTTP. Provenance
        decides what the registry checksum applies to: artifacts the
        fetcher wrote (marked with a ``.src`` sidecar) verify against the
        registered checksum on EVERY load, like the reference's cache; a
        user-placed file is their own choice of weights and only verifies
        when an explicit ``expected_checksum`` is passed. On mismatch, the
        artifact THIS call downloaded is deleted (``ZooModel.java:75-81``,
        so the next call re-fetches); any pre-existing file — even a
        marked cache the user may have replaced — is never deleted, the
        error explains how to recover instead."""
        import zipfile
        import zlib

        path = self.pretrained_checkpoint(pretrained_type)
        downloaded = False
        if path is None:
            url = self.pretrained_url(pretrained_type)
            if url is None:
                raise FileNotFoundError(
                    f"No pretrained weights for {type(self).__name__} ({pretrained_type}); "
                    f"place a checkpoint under $DL4J_TPU_ZOO_DIR or register "
                    f"a PRETRAINED_URLS entry to enable.")
            path = self._fetch(url, self._cache_path(pretrained_type))
            downloaded = True
        fetched = downloaded or os.path.exists(path + ".src")
        if expected_checksum is not None:
            expected = int(expected_checksum)
        else:
            expected = self.pretrained_checksum(pretrained_type) if fetched else 0
        expected_sha = self.pretrained_sha256(pretrained_type) if fetched else ""

        def fail(kind, got, want):
            if downloaded:
                # ZooModel.java:75-81: a corrupt download is removed so
                # the next attempt re-fetches instead of failing forever.
                # Only a file THIS call wrote is ever deleted — a slot
                # the user may have touched since a past fetch is not.
                os.remove(path)
                if os.path.exists(path + ".src"):
                    os.remove(path + ".src")
                raise ValueError(
                    f"Pretrained model file failed checksum: fetched "
                    f"{kind} {got}, expecting {want} ({path}); "
                    "the corrupt download was deleted — retry.")
            if fetched:
                raise ValueError(
                    f"Pretrained model file failed checksum: cached "
                    f"{kind} {got}, expecting {want} ({path}). "
                    "If the cache rotted, delete the file and its .src "
                    "marker to re-fetch; if you placed your own weights "
                    "in this slot, delete just the .src marker.")
            raise ValueError(
                f"Pretrained model file failed checksum: local {kind} "
                f"{got}, expecting {want} ({path}); the file is "
                "left in place — replace it with an intact copy.")

        if expected != 0 or expected_sha:
            import hashlib
            adler = 1  # zlib.adler32 seed, matches java.util.zip.Adler32
            # hash only when a digest is registered — the Adler-only common
            # case must not pay a discarded SHA-256 pass per load
            sha = hashlib.sha256() if expected_sha else None
            with open(path, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    adler = zlib.adler32(chunk, adler)
                    if sha is not None:
                        sha.update(chunk)
            if expected != 0 and adler != expected:
                fail("Adler32", adler, expected)
            # the cryptographic check (when a digest is registered): the
            # Adler32-over-http path alone is corruption detection, not
            # tamper evidence
            if sha is not None and sha.hexdigest() != expected_sha.lower():
                fail("SHA-256", sha.hexdigest(), expected_sha.lower())
        with zipfile.ZipFile(path) as z:
            names = set(z.namelist())
        if "coefficients.bin" in names:  # reference DL4J ModelSerializer zip
            import json as _json
            from deeplearning4j_tpu.modelimport.dl4j import (
                restore_computation_graph, restore_multi_layer_network)
            with zipfile.ZipFile(path) as z:
                raw = z.read("configuration.json").decode("utf-8")
            if "vertices" in _json.loads(raw):
                return restore_computation_graph(path)
            return restore_multi_layer_network(path)
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(path)

    @staticmethod
    def _fetch(url: str, dest: str) -> str:
        """Stream ``url`` into ``dest`` (the cache slot) atomically: bytes
        land in ``dest + '.part'`` first so an interrupted transfer never
        poses as a finished artifact. Scheme-agnostic — ``file://`` and
        ``http(s)://`` share the path (``ZooModel.java:63-66``'s
        ``FileUtils.copyURLToFile`` role)."""
        import shutil
        import urllib.request

        os.makedirs(os.path.dirname(dest), exist_ok=True)
        part = dest + ".part"
        try:
            with urllib.request.urlopen(url) as resp, open(part, "wb") as out:
                shutil.copyfileobj(resp, out)
            # provenance marker BEFORE installing the artifact: a crash
            # between the two steps then leaves a marker with no artifact
            # (harmless — the next call re-fetches and rewrites it), never
            # a fetched artifact without a marker, which would dodge the
            # registry checksum on every later load
            with open(dest + ".src", "w") as fh:
                fh.write(url)
            os.replace(part, dest)
        finally:
            if os.path.exists(part):
                # failed mid-fetch: remove the orphan marker too, so a file
                # the USER later places in the slot is not misattributed to
                # the fetcher (and wrongly checksum-gated)
                os.remove(part)
                if os.path.exists(dest + ".src") and not os.path.exists(dest):
                    os.remove(dest + ".src")
        return dest


_ZOO_REGISTRY: Dict[str, Type[ZooModel]] = {}


def register_zoo_model(cls: Type[ZooModel]) -> Type[ZooModel]:
    _ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ModelSelector:
    """Instantiate zoo models by name (reference ``ModelSelector.java``)."""

    @staticmethod
    def available() -> Sequence[str]:
        return sorted(_ZOO_REGISTRY)

    @staticmethod
    def select(name: str, num_labels: int = 1000, seed: int = 123) -> ZooModel:
        key = name.lower()
        if key not in _ZOO_REGISTRY:
            raise KeyError(f"Unknown zoo model {name!r}; available: {ModelSelector.available()}")
        return _ZOO_REGISTRY[key](num_labels=num_labels, seed=seed)
