"""Model zoo base machinery.

Reference: ``deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/ZooModel.java:51-69``
(pretrained download + checksum + init), ``ModelMetaData.java``, ``ZooType.java``,
``ModelSelector.java``. TPU-native differences: models build straight onto the
functional `MultiLayerNetwork`/`ComputationGraph` configs; pretrained weights
load from a local checkpoint path instead of an HTTP blob store (this image has
no egress), via :mod:`deeplearning4j_tpu.util.model_serializer`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple, Type


@dataclasses.dataclass(frozen=True)
class ModelMetaData:
    """Shape metadata (reference ``ZooModel.metaData()``)."""

    input_shape: Tuple[Tuple[int, ...], ...]  # per graph input, CHW order like DL4J
    n_outputs: int = 1
    network_type: str = "cnn"  # "cnn" | "rnn"

    @property
    def use_mds(self) -> bool:
        return len(self.input_shape) > 1 or self.n_outputs > 1


class PretrainedType:
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


class ZooModel:
    """Base class for zoo architectures (``ZooModel.java``).

    Subclasses implement ``conf()`` (a MultiLayerConfiguration or
    ComputationGraphConfiguration) and ``meta_data()``; ``init()`` builds and
    initializes the runtime network.
    """

    def __init__(self, num_labels: int = 1000, seed: int = 123):
        self.num_labels = num_labels
        self.seed = seed

    # -- to implement ------------------------------------------------------
    def conf(self):
        raise NotImplementedError

    def meta_data(self) -> ModelMetaData:
        raise NotImplementedError

    # -- common ------------------------------------------------------------
    def init(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        c = self.conf()
        if isinstance(c, ComputationGraphConfiguration):
            return ComputationGraph(c).init(seed=self.seed)
        return MultiLayerNetwork(c).init(seed=self.seed)

    def pretrained_checkpoint(self, pretrained_type: str = PretrainedType.IMAGENET) -> Optional[str]:
        """Local path to pretrained weights, or None if unavailable.

        The reference downloads from ``blob.deeplearning4j.org`` with an MD5
        check (``ZooModel.java:51-69``); here weights are looked up under
        ``$DL4J_TPU_ZOO_DIR/<model>_<type>.zip``.
        """
        root = os.environ.get("DL4J_TPU_ZOO_DIR", os.path.expanduser("~/.deeplearning4j_tpu/zoo"))
        p = os.path.join(root, f"{type(self).__name__.lower()}_{pretrained_type}.zip")
        return p if os.path.exists(p) else None

    def init_pretrained(self, pretrained_type: str = PretrainedType.IMAGENET):
        path = self.pretrained_checkpoint(pretrained_type)
        if path is None:
            raise FileNotFoundError(
                f"No pretrained weights for {type(self).__name__} ({pretrained_type}); "
                f"place a checkpoint under $DL4J_TPU_ZOO_DIR to enable.")
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(path)


_ZOO_REGISTRY: Dict[str, Type[ZooModel]] = {}


def register_zoo_model(cls: Type[ZooModel]) -> Type[ZooModel]:
    _ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ModelSelector:
    """Instantiate zoo models by name (reference ``ModelSelector.java``)."""

    @staticmethod
    def available() -> Sequence[str]:
        return sorted(_ZOO_REGISTRY)

    @staticmethod
    def select(name: str, num_labels: int = 1000, seed: int = 123) -> ZooModel:
        key = name.lower()
        if key not in _ZOO_REGISTRY:
            raise KeyError(f"Unknown zoo model {name!r}; available: {ModelSelector.available()}")
        return _ZOO_REGISTRY[key](num_labels=num_labels, seed=seed)
