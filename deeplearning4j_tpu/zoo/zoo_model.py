"""Model zoo base machinery.

Reference: ``deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/ZooModel.java:51-69``
(pretrained download + checksum + init), ``ModelMetaData.java``, ``ZooType.java``,
``ModelSelector.java``. TPU-native differences: models build straight onto the
functional `MultiLayerNetwork`/`ComputationGraph` configs; pretrained weights
load from a local checkpoint path instead of an HTTP blob store (this image has
no egress), via :mod:`deeplearning4j_tpu.util.model_serializer`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple, Type


@dataclasses.dataclass(frozen=True)
class ModelMetaData:
    """Shape metadata (reference ``ZooModel.metaData()``)."""

    input_shape: Tuple[Tuple[int, ...], ...]  # per graph input, CHW order like DL4J
    n_outputs: int = 1
    network_type: str = "cnn"  # "cnn" | "rnn"

    @property
    def use_mds(self) -> bool:
        return len(self.input_shape) > 1 or self.n_outputs > 1


class PretrainedType:
    IMAGENET = "imagenet"
    MNIST = "mnist"
    CIFAR10 = "cifar10"
    VGGFACE = "vggface"


class ZooModel:
    """Base class for zoo architectures (``ZooModel.java``).

    Subclasses implement ``conf()`` (a MultiLayerConfiguration or
    ComputationGraphConfiguration) and ``meta_data()``; ``init()`` builds and
    initializes the runtime network.
    """

    def __init__(self, num_labels: int = 1000, seed: int = 123):
        self.num_labels = num_labels
        self.seed = seed

    # -- to implement ------------------------------------------------------
    def conf(self):
        raise NotImplementedError

    def meta_data(self) -> ModelMetaData:
        raise NotImplementedError

    # -- common ------------------------------------------------------------
    def init(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        c = self.conf()
        if isinstance(c, ComputationGraphConfiguration):
            return ComputationGraph(c).init(seed=self.seed)
        return MultiLayerNetwork(c).init(seed=self.seed)

    def pretrained_checkpoint(self, pretrained_type: str = PretrainedType.IMAGENET) -> Optional[str]:
        """Local path to pretrained weights, or None if unavailable.

        The reference downloads from ``blob.deeplearning4j.org`` with an MD5
        check (``ZooModel.java:51-69``); here weights are looked up under
        ``$DL4J_TPU_ZOO_DIR/<model>_<type>.zip``.
        """
        root = os.environ.get("DL4J_TPU_ZOO_DIR", os.path.expanduser("~/.deeplearning4j_tpu/zoo"))
        p = os.path.join(root, f"{type(self).__name__.lower()}_{pretrained_type}.zip")
        return p if os.path.exists(p) else None

    #: subclasses/users may register expected Adler32 checksums per
    #: pretrained type (``ZooModel.pretrainedChecksum``; 0 = don't verify)
    PRETRAINED_CHECKSUMS: Dict[str, int] = {}

    def pretrained_checksum(self, pretrained_type: str) -> int:
        return int(self.PRETRAINED_CHECKSUMS.get(pretrained_type, 0))

    def init_pretrained(self, pretrained_type: str = PretrainedType.IMAGENET,
                        expected_checksum: Optional[int] = None):
        """Build this architecture carrying pretrained weights
        (``ZooModel.initPretrained``, ``ZooModel.java:51-93``): resolve the
        cached artifact, verify its Adler32 checksum when one is expected,
        then restore through the FULL checkpoint reader — both this
        framework's own zips and the reference's DL4J ModelSerializer zips
        (``coefficients.bin`` + ``updaterState.bin``) load, for
        MultiLayerNetwork and ComputationGraph alike.

        Unlike the reference (which deletes its own downloaded cache on
        mismatch), a user-placed file is never deleted — the error reports
        both checksums instead."""
        import zipfile
        import zlib

        path = self.pretrained_checkpoint(pretrained_type)
        if path is None:
            raise FileNotFoundError(
                f"No pretrained weights for {type(self).__name__} ({pretrained_type}); "
                f"place a checkpoint under $DL4J_TPU_ZOO_DIR to enable.")
        expected = (self.pretrained_checksum(pretrained_type)
                    if expected_checksum is None else int(expected_checksum))
        if expected != 0:
            adler = 1  # zlib.adler32 seed, matches java.util.zip.Adler32
            with open(path, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    adler = zlib.adler32(chunk, adler)
            if adler != expected:
                raise ValueError(
                    f"Pretrained model file failed checksum: local Adler32 "
                    f"{adler}, expecting {expected} ({path}); the file is "
                    "left in place — replace it with an intact copy.")
        with zipfile.ZipFile(path) as z:
            names = set(z.namelist())
        if "coefficients.bin" in names:  # reference DL4J ModelSerializer zip
            import json as _json
            from deeplearning4j_tpu.modelimport.dl4j import (
                restore_computation_graph, restore_multi_layer_network)
            with zipfile.ZipFile(path) as z:
                raw = z.read("configuration.json").decode("utf-8")
            if "vertices" in _json.loads(raw):
                return restore_computation_graph(path)
            return restore_multi_layer_network(path)
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(path)


_ZOO_REGISTRY: Dict[str, Type[ZooModel]] = {}


def register_zoo_model(cls: Type[ZooModel]) -> Type[ZooModel]:
    _ZOO_REGISTRY[cls.__name__.lower()] = cls
    return cls


class ModelSelector:
    """Instantiate zoo models by name (reference ``ModelSelector.java``)."""

    @staticmethod
    def available() -> Sequence[str]:
        return sorted(_ZOO_REGISTRY)

    @staticmethod
    def select(name: str, num_labels: int = 1000, seed: int = 123) -> ZooModel:
        key = name.lower()
        if key not in _ZOO_REGISTRY:
            raise KeyError(f"Unknown zoo model {name!r}; available: {ModelSelector.available()}")
        return _ZOO_REGISTRY[key](num_labels=num_labels, seed=seed)
