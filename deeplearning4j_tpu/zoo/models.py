"""The 13 zoo architectures.

Reference: ``deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/model/``
(AlexNet, Darknet19, FaceNetNN4Small2, GoogLeNet, InceptionResNetV1, LeNet,
ResNet50, SimpleCNN, TextGenerationLSTM, TinyYOLO, VGG16, VGG19, YOLO2).
Configs are built on the TPU-native builder DSL; data layout is NHWC (the
TPU-friendly layout) rather than the reference's NCHW, and convs fold their
batch-norms' scale at inference via XLA fusion rather than cuDNN algo modes.

``ModelMetaData.input_shape`` keeps DL4J's CHW ordering for documentation
parity; actual arrays are NHWC.
"""

from __future__ import annotations

from typing import Tuple

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    GravesLSTMLayer,
    LocalResponseNormalizationLayer,
    LossLayer,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
from deeplearning4j_tpu.nn.layers.output import CenterLossOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, AdaDelta, Nesterovs
from deeplearning4j_tpu.nn.vertices import L2NormalizeVertex, MergeVertex
from deeplearning4j_tpu.zoo.helpers import (
    conv_bn_act,
    darknet_block,
    inception_module,
    inception_resnet_block_a,
    inception_resnet_block_b,
    inception_resnet_block_c,
    resnet_conv_block,
    resnet_identity_block,
)
from deeplearning4j_tpu.zoo.zoo_model import (
    ModelMetaData,
    PretrainedType,
    ZooModel,
    register_zoo_model,
)


@register_zoo_model
class LeNet(ZooModel):
    """LeNet-5-style CNN (``zoo/model/LeNet.java``: 20/50 conv, 500 dense)."""

    # the reference's published artifact registry (LeNet.java:58-70); these
    # DL4J ModelSerializer zips restore through our DL4J reader when fetched
    PRETRAINED_URLS = {PretrainedType.MNIST:
                       "http://blob.deeplearning4j.org/models/lenet_dl4j_mnist_inference.zip"}
    PRETRAINED_CHECKSUMS = {PretrainedType.MNIST: 1906861161}

    def __init__(self, num_labels: int = 10, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (1, 28, 28)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        return (NeuralNetConfiguration.builder().seed(self.seed)
                .activation("identity").weight_init("xavier")
                .updater(AdaDelta()).list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                        convolution_mode="same", activation="identity"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                        convolution_mode="same", activation="identity"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_labels, loss="mcxent", activation="softmax"))
                .set_input_type(InputType.convolutional(h, w, c)).build())


@register_zoo_model
class SimpleCNN(ZooModel):
    """Conv/BN/avg-pool stack ending in a fully convolutional softmax head
    (``zoo/model/SimpleCNN.java:77-125``)."""

    def __init__(self, num_labels: int = 10, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 48, 48)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        b = (NeuralNetConfiguration.builder().seed(self.seed)
             .activation("relu").weight_init("relu").updater(AdaDelta()).list())
        # block 1: two 7x7 convs @16
        b.layer(ConvolutionLayer(n_out=16, kernel_size=(7, 7), convolution_mode="same"))
        b.layer(BatchNormalizationLayer())
        b.layer(ConvolutionLayer(n_out=16, kernel_size=(7, 7), convolution_mode="same"))
        b.layer(BatchNormalizationLayer())
        b.layer(ActivationLayer(activation="relu"))
        b.layer(SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2)))
        b.layer(DropoutLayer(dropout=0.5))
        for n in (32, 64, 128):
            k = 5 if n == 32 else 3
            b.layer(ConvolutionLayer(n_out=n, kernel_size=(k, k), convolution_mode="same"))
            b.layer(BatchNormalizationLayer())
            b.layer(ConvolutionLayer(n_out=n, kernel_size=(k, k), convolution_mode="same"))
            b.layer(BatchNormalizationLayer())
            b.layer(ActivationLayer(activation="relu"))
            b.layer(SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2)))
            b.layer(DropoutLayer(dropout=0.5))
        b.layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), convolution_mode="same"))
        b.layer(BatchNormalizationLayer())
        b.layer(ConvolutionLayer(n_out=self.num_labels, kernel_size=(3, 3),
                                 convolution_mode="same", activation="identity"))
        b.layer(GlobalPoolingLayer(pooling_type="avg"))
        b.layer(LossLayer(loss="mcxent", activation="softmax"))
        return b.set_input_type(InputType.convolutional(h, w, c)).build()


@register_zoo_model
class AlexNet(ZooModel):
    """AlexNet (one-tower variant, ``zoo/model/AlexNet.java``)."""

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 224, 224)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        from deeplearning4j_tpu.nn.weights import Distribution
        return (NeuralNetConfiguration.builder().seed(self.seed)
                .activation("relu")
                .weight_init("distribution", Distribution("normal", 0.0, 0.005))
                .updater(Nesterovs(1e-2, 0.9)).l2(5e-4).list()
                .layer(ConvolutionLayer(n_out=64, kernel_size=(11, 11), stride=(4, 4),
                                        padding=(3, 3)))
                .layer(LocalResponseNormalizationLayer())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=192, kernel_size=(5, 5), convolution_mode="same"))
                .layer(LocalResponseNormalizationLayer())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3), convolution_mode="same"))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), convolution_mode="same"))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3), convolution_mode="same"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, dropout=0.5))
                .layer(DenseLayer(n_out=4096, dropout=0.5))
                .layer(OutputLayer(n_out=self.num_labels, loss="mcxent", activation="softmax"))
                .set_input_type(InputType.convolutional(h, w, c)).build())


def _vgg_conf(blocks, num_labels, seed, input_shape):
    c, h, w = input_shape
    b = (NeuralNetConfiguration.builder().seed(seed)
         .activation("relu").weight_init("xavier").updater(Nesterovs(1e-2, 0.9)).list())
    for n_convs, n_out in blocks:
        for _ in range(n_convs):
            b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3), convolution_mode="same"))
        b.layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
    b.layer(DenseLayer(n_out=4096, dropout=0.5))
    b.layer(DenseLayer(n_out=4096, dropout=0.5))
    b.layer(OutputLayer(n_out=num_labels, loss="mcxent", activation="softmax"))
    return b.set_input_type(InputType.convolutional(h, w, c)).build()


@register_zoo_model
class VGG16(ZooModel):
    """VGG-16 (``zoo/model/VGG16.java``; Simonyan & Zisserman 2014)."""

    # published artifacts (VGG16.java:58-79)
    PRETRAINED_URLS = {
        PretrainedType.IMAGENET: "http://blob.deeplearning4j.org/models/vgg16_dl4j_inference.zip",
        PretrainedType.CIFAR10: "http://blob.deeplearning4j.org/models/vgg16_dl4j_cifar10_inference.v1.zip",
        PretrainedType.VGGFACE: "http://blob.deeplearning4j.org/models/vgg16_dl4j_vggface_inference.v1.zip",
    }
    PRETRAINED_CHECKSUMS = {PretrainedType.IMAGENET: 3501732770,
                            PretrainedType.CIFAR10: 2192260131,
                            PretrainedType.VGGFACE: 2706403553}

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 224, 224)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        return _vgg_conf([(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)],
                         self.num_labels, self.seed, self.input_shape)


@register_zoo_model
class VGG19(ZooModel):
    """VGG-19 (``zoo/model/VGG19.java``)."""

    PRETRAINED_URLS = {PretrainedType.IMAGENET:
                       "http://blob.deeplearning4j.org/models/vgg19_dl4j_inference.zip"}
    PRETRAINED_CHECKSUMS = {PretrainedType.IMAGENET: 2782932419}

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 224, 224)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        return _vgg_conf([(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)],
                         self.num_labels, self.seed, self.input_shape)


@register_zoo_model
class Darknet19(ZooModel):
    """Darknet-19 classifier (``zoo/model/Darknet19.java`` via DarknetHelper).

    The published artifact depends on the input resolution
    (``Darknet19.java:60-76``) — :meth:`pretrained_url` and
    :meth:`pretrained_checksum` override the registries accordingly."""

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 224, 224)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def _artifact_name(self, pretrained_type):
        # 224 and 448 weights are different artifacts (different URLs and
        # checksums) — they must not share one cache slot
        if self.input_shape[1] == 448 and self.input_shape[2] == 448:
            return f"darknet19_448_{pretrained_type}.zip"
        return f"darknet19_{pretrained_type}.zip"

    def pretrained_url(self, pretrained_type):
        if pretrained_type != PretrainedType.IMAGENET:
            return None
        if self.input_shape[1] == 448 and self.input_shape[2] == 448:
            return "http://blob.deeplearning4j.org/models/darknet19_448_dl4j_inference.v1.zip"
        return "http://blob.deeplearning4j.org/models/darknet19_dl4j_inference.v1.zip"

    def pretrained_checksum(self, pretrained_type):
        if pretrained_type != PretrainedType.IMAGENET:
            return 0
        if self.input_shape[1] == 448 and self.input_shape[2] == 448:
            return 870575230
        return 3952910425

    def conf(self):
        c, h, w = self.input_shape
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .weight_init("xavier").updater(Nesterovs(1e-3, 0.9)).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        x = darknet_block(g, 1, "input", 32, pool=2)
        x = darknet_block(g, 2, x, 64, pool=2)
        x = darknet_block(g, 3, x, 128)
        x = darknet_block(g, 4, x, 64, filter_size=1)
        x = darknet_block(g, 5, x, 128, pool=2)
        x = darknet_block(g, 6, x, 256)
        x = darknet_block(g, 7, x, 128, filter_size=1)
        x = darknet_block(g, 8, x, 256, pool=2)
        x = darknet_block(g, 9, x, 512)
        x = darknet_block(g, 10, x, 256, filter_size=1)
        x = darknet_block(g, 11, x, 512)
        x = darknet_block(g, 12, x, 256, filter_size=1)
        x = darknet_block(g, 13, x, 512, pool=2)
        x = darknet_block(g, 14, x, 1024)
        x = darknet_block(g, 15, x, 512, filter_size=1)
        x = darknet_block(g, 16, x, 1024)
        x = darknet_block(g, 17, x, 512, filter_size=1)
        x = darknet_block(g, 18, x, 1024)
        g.add_layer("convolution2d_19",
                    ConvolutionLayer(n_out=self.num_labels, kernel_size=(1, 1),
                                     convolution_mode="same", activation="identity"), x)
        g.add_layer("globalpooling", GlobalPoolingLayer(pooling_type="avg"),
                    "convolution2d_19")
        g.add_layer("loss", LossLayer(loss="mcxent", activation="softmax"),
                    "globalpooling")
        return g.set_outputs("loss").build()


# Anchor priors from the reference (TinyYOLO.java / YOLO2.java), grid units.
TINY_YOLO_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11), (16.62, 10.52))
YOLO2_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
                 (7.88282, 3.52778), (9.77052, 9.16828))


@register_zoo_model
class TinyYOLO(ZooModel):
    """Tiny YOLOv2 detector (``zoo/model/TinyYOLO.java``)."""

    PRETRAINED_URLS = {PretrainedType.IMAGENET:
                       "http://blob.deeplearning4j.org/models/tiny-yolo-voc_dl4j_inference.v1.zip"}
    PRETRAINED_CHECKSUMS = {PretrainedType.IMAGENET: 2004171617}

    def __init__(self, num_labels: int = 20, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 416, 416)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        nb = len(TINY_YOLO_ANCHORS)
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .weight_init("xavier").updater(Adam(1e-3)).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        x = darknet_block(g, 1, "input", 16, pool=2)
        x = darknet_block(g, 2, x, 32, pool=2)
        x = darknet_block(g, 3, x, 64, pool=2)
        x = darknet_block(g, 4, x, 128, pool=2)
        x = darknet_block(g, 5, x, 256, pool=2)
        x = darknet_block(g, 6, x, 512, pool=2, pool_stride=1)
        x = darknet_block(g, 7, x, 1024)
        x = darknet_block(g, 8, x, 1024)
        g.add_layer("convolution2d_9",
                    ConvolutionLayer(n_out=nb * (5 + self.num_labels), kernel_size=(1, 1),
                                     convolution_mode="same", activation="identity"), x)
        g.add_layer("outputs", Yolo2OutputLayer(boxes=TINY_YOLO_ANCHORS,
                                                n_classes=self.num_labels),
                    "convolution2d_9")
        return g.set_outputs("outputs").build()


@register_zoo_model
class YOLO2(ZooModel):
    """YOLOv2 with Darknet-19 backbone + passthrough reorg
    (``zoo/model/YOLO2.java``: SpaceToDepth passthrough merged before head)."""

    PRETRAINED_URLS = {PretrainedType.IMAGENET:
                       "http://blob.deeplearning4j.org/models/yolo2_dl4j_inference.v1.zip"}
    PRETRAINED_CHECKSUMS = {PretrainedType.IMAGENET: 1357637732}

    def __init__(self, num_labels: int = 80, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 608, 608)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        from deeplearning4j_tpu.nn.layers.conv import SpaceToDepthLayer
        c, h, w = self.input_shape
        nb = len(YOLO2_ANCHORS)
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .weight_init("xavier").updater(Adam(1e-3)).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        x = darknet_block(g, 1, "input", 32, pool=2)
        x = darknet_block(g, 2, x, 64, pool=2)
        x = darknet_block(g, 3, x, 128)
        x = darknet_block(g, 4, x, 64, filter_size=1)
        x = darknet_block(g, 5, x, 128, pool=2)
        x = darknet_block(g, 6, x, 256)
        x = darknet_block(g, 7, x, 128, filter_size=1)
        x = darknet_block(g, 8, x, 256, pool=2)
        x = darknet_block(g, 9, x, 512)
        x = darknet_block(g, 10, x, 256, filter_size=1)
        x = darknet_block(g, 11, x, 512)
        x = darknet_block(g, 12, x, 256, filter_size=1)
        passthrough = darknet_block(g, 13, x, 512)  # 1/16 resolution feature map
        g.add_layer("maxpooling2d_13",
                    SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)),
                    passthrough)
        x = darknet_block(g, 14, "maxpooling2d_13", 1024)
        x = darknet_block(g, 15, x, 512, filter_size=1)
        x = darknet_block(g, 16, x, 1024)
        x = darknet_block(g, 17, x, 512, filter_size=1)
        x = darknet_block(g, 18, x, 1024)
        x = darknet_block(g, 19, x, 1024)
        x = darknet_block(g, 20, x, 1024)
        # passthrough: reorg 1/16 map to 1/32 and concat with the deep map
        g.add_layer("reorg", SpaceToDepthLayer(block_size=2), passthrough)
        g.add_vertex("concat", MergeVertex(), "reorg", x)
        x = darknet_block(g, 21, "concat", 1024)
        g.add_layer("convolution2d_22",
                    ConvolutionLayer(n_out=nb * (5 + self.num_labels), kernel_size=(1, 1),
                                     convolution_mode="same", activation="identity"), x)
        g.add_layer("outputs", Yolo2OutputLayer(boxes=YOLO2_ANCHORS,
                                                n_classes=self.num_labels),
                    "convolution2d_22")
        return g.set_outputs("outputs").build()


@register_zoo_model
class ResNet50(ZooModel):
    """ResNet-50 (``zoo/model/ResNet50.java:89-216``): 7x7 stem then
    [3,4,6,3] bottleneck stages."""

    PRETRAINED_URLS = {PretrainedType.IMAGENET:
                       "http://blob.deeplearning4j.org/models/resnet50_dl4j_inference.zip"}
    PRETRAINED_CHECKSUMS = {PretrainedType.IMAGENET: 1982516793}

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 224, 224)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .activation("identity").weight_init("xavier")
             .updater(Nesterovs(1e-2, 0.9)).l1(1e-7).l2(5e-5).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        g.add_layer("stem-zero", ZeroPaddingLayer(padding=(3, 3)), "input")
        g.add_layer("stem-cnn1",
                    ConvolutionLayer(n_out=64, kernel_size=(7, 7), stride=(2, 2),
                                     activation="identity", has_bias=False,
                                     space_to_depth_stem=True), "stem-zero")
        g.add_layer("stem-batch1", BatchNormalizationLayer(activation="identity"), "stem-cnn1")
        g.add_layer("stem-act1", ActivationLayer(activation="relu"), "stem-batch1")
        g.add_layer("stem-maxpool1",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2)),
                    "stem-act1")
        # canonical ResNet-50 stride-1 projection at stage 2 (the stem maxpool
        # already downsampled); the reference's ResNet50.java:194 passes {2,2}
        # here, a known deviation that breaks pretrained-weight compatibility
        x = resnet_conv_block(g, (3, 3), (64, 64, 256), "2", "a", "stem-maxpool1",
                              stride=(1, 1))
        x = resnet_identity_block(g, (3, 3), (64, 64, 256), "2", "b", x)
        x = resnet_identity_block(g, (3, 3), (64, 64, 256), "2", "c", x)
        x = resnet_conv_block(g, (3, 3), (128, 128, 512), "3", "a", x)
        for blk in "bcd":
            x = resnet_identity_block(g, (3, 3), (128, 128, 512), "3", blk, x)
        x = resnet_conv_block(g, (3, 3), (256, 256, 1024), "4", "a", x)
        for blk in "bcdef":
            x = resnet_identity_block(g, (3, 3), (256, 256, 1024), "4", blk, x)
        x = resnet_conv_block(g, (3, 3), (512, 512, 2048), "5", "a", x)
        for blk in "bc":
            x = resnet_identity_block(g, (3, 3), (512, 512, 2048), "5", blk, x)
        g.add_layer("avgpool",
                    SubsamplingLayer(pooling_type="avg", kernel_size=(3, 3), stride=(1, 1),
                                     convolution_mode="same"), x)
        g.add_layer("globalpool", GlobalPoolingLayer(pooling_type="avg"), "avgpool")
        g.add_layer("fc1000", OutputLayer(n_out=self.num_labels, loss="mcxent",
                                          activation="softmax"), "globalpool")
        return g.set_outputs("fc1000").build()


@register_zoo_model
class GoogLeNet(ZooModel):
    """GoogLeNet / Inception-v1 (``zoo/model/GoogLeNet.java``)."""

    PRETRAINED_URLS = {PretrainedType.IMAGENET:
                       "http://blob.deeplearning4j.org/models/googlenet_dl4j_inference.zip"}
    PRETRAINED_CHECKSUMS = {PretrainedType.IMAGENET: 3337733202}

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 224, 224)):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .activation("relu").weight_init("xavier")
             .updater(Nesterovs(1e-2, 0.9)).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        g.add_layer("cnn1", ConvolutionLayer(n_out=64, kernel_size=(7, 7), stride=(2, 2),
                                             convolution_mode="same"), "input")
        g.add_layer("max1", SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                             stride=(2, 2), convolution_mode="same"), "cnn1")
        g.add_layer("lrn1", LocalResponseNormalizationLayer(), "max1")
        g.add_layer("cnn2", ConvolutionLayer(n_out=64, kernel_size=(1, 1)), "lrn1")
        g.add_layer("cnn3", ConvolutionLayer(n_out=192, kernel_size=(3, 3),
                                             convolution_mode="same"), "cnn2")
        g.add_layer("lrn2", LocalResponseNormalizationLayer(), "cnn3")
        g.add_layer("max2", SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                             stride=(2, 2), convolution_mode="same"), "lrn2")
        x = inception_module(g, "3a", "max2", 64, 96, 128, 16, 32, 32)
        x = inception_module(g, "3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("max3", SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                             stride=(2, 2), convolution_mode="same"), x)
        x = inception_module(g, "4a", "max3", 192, 96, 208, 16, 48, 64)
        x = inception_module(g, "4b", x, 160, 112, 224, 24, 64, 64)
        x = inception_module(g, "4c", x, 128, 128, 256, 24, 64, 64)
        x = inception_module(g, "4d", x, 112, 144, 288, 32, 64, 64)
        x = inception_module(g, "4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("max4", SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                             stride=(2, 2), convolution_mode="same"), x)
        x = inception_module(g, "5a", "max4", 256, 160, 320, 32, 128, 128)
        x = inception_module(g, "5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.4), "avgpool")
        g.add_layer("output", OutputLayer(n_out=self.num_labels, loss="mcxent",
                                          activation="softmax"), "dropout")
        return g.set_outputs("output").build()


@register_zoo_model
class InceptionResNetV1(ZooModel):
    """Inception-ResNet-v1 with center-loss embedding head
    (``zoo/model/InceptionResNetV1.java``: stem → 5×A → reduction →
    10×B → reduction → 5×C → bottleneck → center-loss output)."""

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 160, 160),
                 embedding_size: int = 128):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape
        self.embedding_size = embedding_size

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def _stem(self, g, inp):
        x = conv_bn_act(g, "stem-1", inp, 32, (3, 3), (2, 2))
        x = conv_bn_act(g, "stem-2", x, 32, (3, 3))
        x = conv_bn_act(g, "stem-3", x, 64, (3, 3))
        g.add_layer("stem-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        x = conv_bn_act(g, "stem-4", "stem-pool", 80, (1, 1))
        x = conv_bn_act(g, "stem-5", x, 192, (3, 3))
        x = conv_bn_act(g, "stem-6", x, 256, (3, 3), (2, 2))
        return x

    def conf(self):
        c, h, w = self.input_shape
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .activation("relu").weight_init("relu")
             .updater(Adam(1e-3)).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        x = self._stem(g, "input")
        for i in range(5):
            x = inception_resnet_block_a(g, f"block35-{i}", x, 0.17)
        # reduction A: 256 → 896 channels, spatial /2
        ra_b1 = conv_bn_act(g, "redA-b1", x, 384, (3, 3), (2, 2))
        ra_b2a = conv_bn_act(g, "redA-b2a", x, 192, (1, 1))
        ra_b2b = conv_bn_act(g, "redA-b2b", ra_b2a, 192, (3, 3))
        ra_b2 = conv_bn_act(g, "redA-b2c", ra_b2b, 256, (3, 3), (2, 2))
        g.add_layer("redA-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        g.add_vertex("redA", MergeVertex(), ra_b1, ra_b2, "redA-pool")
        x = "redA"
        for i in range(10):
            x = inception_resnet_block_b(g, f"block17-{i}", x, 0.10)
        # reduction B: 896 → 1792, spatial /2
        rb_b1a = conv_bn_act(g, "redB-b1a", x, 256, (1, 1))
        rb_b1 = conv_bn_act(g, "redB-b1b", rb_b1a, 384, (3, 3), (2, 2))
        rb_b2a = conv_bn_act(g, "redB-b2a", x, 256, (1, 1))
        rb_b2 = conv_bn_act(g, "redB-b2b", rb_b2a, 256, (3, 3), (2, 2))
        rb_b3a = conv_bn_act(g, "redB-b3a", x, 256, (1, 1))
        rb_b3b = conv_bn_act(g, "redB-b3b", rb_b3a, 256, (3, 3))
        rb_b3 = conv_bn_act(g, "redB-b3c", rb_b3b, 256, (3, 3), (2, 2))
        g.add_layer("redB-pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        g.add_vertex("redB", MergeVertex(), rb_b1, rb_b2, rb_b3, "redB-pool")
        x = "redB"
        for i in range(5):
            x = inception_resnet_block_c(g, f"block8-{i}", x, 0.20)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.8), "avgpool")
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "dropout")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("lossLayer",
                    CenterLossOutputLayer(n_out=self.num_labels, loss="mcxent",
                                          activation="softmax", alpha=0.9, lambda_=1e-4),
                    "embeddings")
        return g.set_outputs("lossLayer").build()


@register_zoo_model
class FaceNetNN4Small2(ZooModel):
    """FaceNet NN4.small2 embedding net (``zoo/model/FaceNetNN4Small2.java``):
    inception-style trunk → 128-d L2-normalized embedding → center loss."""

    def __init__(self, num_labels: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, int, int] = (3, 96, 96),
                 embedding_size: int = 128):
        super().__init__(num_labels, seed)
        self.input_shape = input_shape
        self.embedding_size = embedding_size

    def meta_data(self):
        return ModelMetaData((self.input_shape,), 1, "cnn")

    def conf(self):
        c, h, w = self.input_shape
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .activation("relu").weight_init("relu")
             .updater(Adam(0.1)).graph_builder()
             .add_inputs("input").set_input_types(InputType.convolutional(h, w, c)))
        x = conv_bn_act(g, "stem-cnn1", "input", 64, (7, 7), (2, 2))
        g.add_layer("stem-pool1",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        x = conv_bn_act(g, "inception-2", "stem-pool1", 64, (1, 1))
        x = conv_bn_act(g, "inception-3", x, 192, (3, 3))
        g.add_layer("stem-pool2",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        x = inception_module(g, "3a", "stem-pool2", 64, 96, 128, 16, 32, 32)
        x = inception_module(g, "3b", x, 64, 96, 128, 32, 64, 64)
        g.add_layer("pool3",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        x = inception_module(g, "4a", "pool3", 256, 96, 192, 32, 64, 128)
        x = inception_module(g, "4e", x, 160, 128, 256, 32, 64, 128)
        g.add_layer("pool4",
                    SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        x = inception_module(g, "5a", "pool4", 256, 96, 384, 24, 64, 96)
        x = inception_module(g, "5b", x, 256, 96, 384, 24, 64, 96)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("lossLayer",
                    CenterLossOutputLayer(n_out=self.num_labels, loss="mcxent",
                                          activation="softmax", alpha=0.9, lambda_=1e-4),
                    "embeddings")
        return g.set_outputs("lossLayer").build()


@register_zoo_model
class TextGenerationLSTM(ZooModel):
    """Char-level text generation LSTM (``zoo/model/TextGenerationLSTM.java:81-86``:
    2× GravesLSTM(256) → RnnOutputLayer MCXENT)."""

    def __init__(self, num_labels: int = 26, seed: int = 123, max_length: int = 40):
        super().__init__(num_labels, seed)
        self.max_length = max_length

    def meta_data(self):
        return ModelMetaData(((self.max_length, self.num_labels),), 1, "rnn")

    def conf(self):
        return (NeuralNetConfiguration.builder().seed(self.seed)
                .weight_init("xavier").updater("rmsprop")
                .l2(0.001)
                .gradient_normalization("clip_elementwise_absolute_value", 10.0).list()
                .layer(GravesLSTMLayer(n_in=self.num_labels, n_out=256, activation="tanh"))
                .layer(GravesLSTMLayer(n_out=256, activation="tanh"))
                .layer(RnnOutputLayer(n_out=self.num_labels, loss="mcxent",
                                      activation="softmax"))
                .set_input_type(InputType.recurrent(self.num_labels, self.max_length))
                .build())


def transformer_decoder_block(g, name: str, src: str, d_model: int,
                              n_heads: int, d_ff: int, max_len: int,
                              attn_dropout: float = 0.0) -> str:
    """One pre-LN causal decoder block (GPT-style): LN → causal self-attention
    → residual, LN → position-wise FFN → residual. Pre-LN because it trains
    stably without warmup — the modern decoder default. Returns the output
    vertex name."""
    from deeplearning4j_tpu.nn.layers import (
        CausalSelfAttentionLayer,
        LayerNormalizationLayer,
    )
    from deeplearning4j_tpu.nn.vertices import ElementWiseVertex

    g.add_layer(f"{name}-ln1", LayerNormalizationLayer(), src)
    g.add_layer(f"{name}-att",
                CausalSelfAttentionLayer(n_heads=n_heads,
                                         head_size=d_model // n_heads,
                                         project_input=True,
                                         max_cache=max_len,
                                         attn_dropout=attn_dropout),
                f"{name}-ln1")
    g.add_vertex(f"{name}-res1", ElementWiseVertex(op="add"),
                 src, f"{name}-att")
    g.add_layer(f"{name}-ln2", LayerNormalizationLayer(), f"{name}-res1")
    g.add_layer(f"{name}-ff1", DenseLayer(n_in=d_model, n_out=d_ff,
                                          activation="gelu"), f"{name}-ln2")
    g.add_layer(f"{name}-ff2", DenseLayer(n_in=d_ff, n_out=d_model,
                                          activation="identity"),
                f"{name}-ff1")
    g.add_vertex(f"{name}-res2", ElementWiseVertex(op="add"),
                 f"{name}-res1", f"{name}-ff2")
    return f"{name}-res2"


@register_zoo_model
class TransformerLM(ZooModel):
    """GPT-style causal-decoder language model — the attention-era successor
    of ``TextGenerationLSTM`` (``zoo/model/TextGenerationLSTM.java``): token
    ids [N,T] → embedding + learned positions → n pre-LN causal decoder
    blocks → final LayerNorm → per-timestep softmax over the vocabulary
    (RnnOutputLayer, MCXENT). Labels are the inputs shifted left by one
    (see :func:`lm_labels`).

    Generation uses the network's stateful ``rnn_time_step`` path: every
    causal attention layer carries a fixed-capacity KV cache, so sampling N
    tokens is N jitted single-token steps, not N quadratic re-forwards.
    Defaults are GPT-2-small shape (12L / 768 / 12H / 3072).
    """

    def __init__(self, num_labels: int = 0, seed: int = 123,
                 vocab_size: int = 50257, max_length: int = 1024,
                 n_layers: int = 12, d_model: int = 768, n_heads: int = 12,
                 d_ff: int = 3072, attn_dropout: float = 0.0):
        # for an LM the label space IS the vocabulary: num_labels, when
        # given (e.g. via ModelSelector), overrides vocab_size — the same
        # convention as TextGenerationLSTM(num_labels=vocab)
        vocab_size = num_labels or vocab_size
        super().__init__(vocab_size, seed)
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.n_layers = n_layers
        self.d_model = d_model
        self.n_heads = n_heads
        self.d_ff = d_ff
        self.attn_dropout = attn_dropout

    def meta_data(self):
        return ModelMetaData(((self.max_length,),), 1, "rnn")

    def conf(self):
        from deeplearning4j_tpu.nn.layers import (
            EmbeddingSequenceLayer,
            LayerNormalizationLayer,
            PositionalEmbeddingLayer,
        )

        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .weight_init("xavier").updater(Adam(3e-4)).graph_builder()
             .add_inputs("tokens")
             .set_input_types(InputType.recurrent(1, self.max_length)))
        g.add_layer("embed",
                    EmbeddingSequenceLayer(n_in=self.vocab_size,
                                           n_out=self.d_model), "tokens")
        g.add_layer("pos", PositionalEmbeddingLayer(n_in=self.d_model,
                                                    max_len=self.max_length),
                    "embed")
        src = "pos"
        for i in range(self.n_layers):
            src = transformer_decoder_block(g, f"block{i}", src,
                                            self.d_model, self.n_heads,
                                            self.d_ff, self.max_length,
                                            self.attn_dropout)
        g.add_layer("ln_f", LayerNormalizationLayer(), src)
        g.add_layer("out", RnnOutputLayer(n_in=self.d_model,
                                          n_out=self.vocab_size,
                                          activation="softmax", loss="mcxent"),
                    "ln_f")
        g.set_outputs("out")
        return g.build()


def lm_labels(tokens, vocab_size: int):
    """Next-token one-hot targets for causal LM training: labels[t] =
    onehot(tokens[t+1]); the last step repeats the last token (give it a
    [N,T] label mask with 0 in the final column to drop it from the loss)."""
    import numpy as np
    ids = np.asarray(tokens).astype(np.int64)
    shifted = np.concatenate([ids[:, 1:], ids[:, -1:]], axis=1)
    out = np.zeros(shifted.shape + (vocab_size,), np.float32)
    np.put_along_axis(out, shifted[..., None], 1.0, axis=-1)
    return out


def generate(net, prompt_ids, n_new_tokens: int, temperature: float = 0.0,
             seed: int = 0):
    """Autoregressive sampling from a trained :class:`TransformerLM` network.

    Feeds the whole prompt through the stateful KV-cached path once, then
    samples one token per jitted step (n_new_tokens - 1 incremental steps
    total — the last sampled token is not fed back). ``temperature=0`` is
    greedy argmax. Returns [N, n_new_tokens] generated ids.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    ids, empty = _prep_prompt(net, prompt_ids, n_new_tokens)
    if empty is not None:
        return empty
    net.rnn_clear_previous_state()
    # [N,T,1] so rnn_time_step keeps the time axis (ids are "features")
    probs = np.asarray(net.rnn_time_step(ids[:, :, None].astype(np.float32)))
    out = []
    for i in range(n_new_tokens):
        p_last = probs[:, -1, :] if probs.ndim == 3 else probs
        if temperature and temperature > 0:
            logits = np.log(np.maximum(p_last, 1e-20)) / temperature
            z = np.exp(logits - logits.max(axis=-1, keepdims=True))
            p = (z / z.sum(axis=-1, keepdims=True)).astype(np.float64)
            p /= p.sum(axis=-1, keepdims=True)  # exact for rng.choice's check
            nxt = np.array([rng.choice(p.shape[-1], p=row) for row in p])
        else:
            nxt = np.argmax(p_last, axis=-1)
        out.append(nxt)
        if i < n_new_tokens - 1:
            probs = np.asarray(
                net.rnn_time_step(nxt[:, None, None].astype(np.float32)))
    return np.stack(out, axis=1)


def generate_on_device(net, prompt_ids, n_new_tokens: int,
                       temperature: float = 0.0, seed: int = 0,
                       top_k: int = 0, top_p: float = 0.0):
    """Autoregressive sampling compiled to ONE device executable: prompt
    prefill fills every KV cache, then a ``lax.scan`` decodes one token per
    step with on-device argmax/categorical sampling. A single dispatch and a
    single host read for the whole sequence — the TPU-idiomatic decode loop
    (the host-loop :func:`generate` pays one device round-trip per token,
    which dominates when the link to the chip is remote).

    Greedy (``temperature=0``) matches :func:`generate` exactly; sampling
    uses ``jax.random.categorical`` (a different RNG than the host loop's
    numpy, so draws differ — distributions match). ``top_k`` keeps only the
    k most likely tokens and ``top_p`` keeps the smallest nucleus whose
    probability mass reaches p (both on-device filters over the temperature-
    scaled distribution; combine freely — top_k applies first). Returns
    [N, n_new_tokens].
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    _require_graph(net, "generate_on_device")
    ids, empty = _prep_prompt(net, prompt_ids, n_new_tokens)
    if empty is not None:
        return empty

    from deeplearning4j_tpu.nn import helpers as _helpers
    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer

    inp = net.conf.inputs[0]
    out_name = net.conf.outputs[0]
    greedy = not (temperature and temperature > 0)
    vocab_n = getattr(net.conf.vertices[out_name].obj, "n_out", 0)
    if greedy or top_k < 0 or (vocab_n and top_k >= vocab_n):
        top_k = 0  # no-op filter: don't let it fragment the compile cache
    if greedy or not (top_p and 0.0 < top_p < 1.0):
        top_p = 0.0
    key = ("generate", n_new_tokens, greedy, float(temperature),
           int(top_k), float(top_p), _helpers.version())
    if key not in net._jit_cache:
        net._evict_stale(_helpers.version())
        dtype = net.conf.global_conf.jnp_dtype()

        use_k = bool(top_k and top_k > 0)
        use_p = bool(top_p and 0.0 < top_p < 1.0)

        def sample(p, k):
            if greedy:
                return jnp.argmax(p, axis=-1).astype(jnp.int32)
            logits = jnp.log(jnp.maximum(p, 1e-20)) / temperature
            if use_k or use_p:
                srt = jnp.sort(logits, axis=-1)[:, ::-1]  # ONE descending sort
                if use_k:
                    kk = min(int(top_k), p.shape[-1])
                    logits = jnp.where(logits >= srt[..., kk - 1][..., None],
                                       logits, -jnp.inf)
                    # the nucleus then applies over the top-k survivors
                    srt = jnp.where(jnp.arange(srt.shape[-1]) < kk, srt,
                                    -jnp.inf)
                if use_p:
                    # keep the smallest prefix reaching mass top_p (>= 1 tok)
                    probs = jax.nn.softmax(srt, axis=-1)
                    csum = jnp.cumsum(probs, axis=-1)
                    keep = csum - probs < top_p
                    cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                                     keepdims=True)
                    logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
            return jax.random.categorical(k, logits, axis=-1).astype(jnp.int32)

        def fn(params, states, prompt, rng_key):
            batch = prompt.shape[0]
            carries = {vd.name: vd.obj.init_carry(batch, dtype)
                       for vd in net.conf.layer_vertices()
                       if isinstance(vd.obj, BaseRecurrentLayer)}
            acts, _, _, carries = net._forward_all(
                params, states, {inp: prompt}, train=False, rng=None,
                carries=carries)
            keys = jax.random.split(rng_key, n_new_tokens)
            tok0 = sample(acts[out_name][:, -1], keys[0])

            def step(carry, k):
                carries, tok = carry
                x = tok[:, None, None].astype(dtype)
                acts, _, _, carries = net._forward_all(
                    params, states, {inp: x}, train=False, rng=None,
                    carries=carries)
                nxt = sample(acts[out_name][:, -1], k)
                return (carries, nxt), nxt

            _, toks = jax.lax.scan(step, (carries, tok0), keys[1:])
            return jnp.concatenate([tok0[:, None], toks.T], axis=1)

        net._jit_cache[key] = jax.jit(fn)
    toks = net._jit_cache[key](net.params, net.states,
                               jnp.asarray(ids, jnp.float32),
                               jax.random.PRNGKey(seed))
    return np.asarray(toks).astype(np.int64)


def beam_search(net, prompt_ids, n_new_tokens: int, beam_size: int = 4,
                eos_id: int = None, length_penalty: float = 0.0):
    """Device-side beam search over a :class:`TransformerLM`-style network:
    the beams ride the batch axis (N*beam KV caches), each `lax.scan` step
    scores beam*vocab continuations, takes the top-k, and RE-INDEXES every
    per-beam carry (KV caches included) with one gather — the whole search
    is a single compiled dispatch, like :func:`generate_on_device`.

    With ``eos_id``, finished beams only extend with ``eos_id`` at zero
    cost (score frozen). Raw scores are unnormalized log-prob sums, which
    favor beams that hit EOS early (shorter sums are less negative);
    ``length_penalty`` > 0 corrects that early-termination bias by ranking
    beams on ``score / length**length_penalty`` (GNMT-style; 1.0 = mean
    log-prob per token, 0.0 = raw sums, the biased legacy behavior).
    Returns ``(tokens [N, n_new_tokens], scores [N])`` for the best beam
    per batch row; scores are the ranking values (normalized when
    ``length_penalty`` > 0).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    _require_graph(net, "beam_search")
    if length_penalty < 0:
        raise ValueError(
            f"length_penalty must be >= 0 (got {length_penalty}); 0 disables "
            "normalization, larger values favor longer beams")
    ids, empty = _prep_prompt(net, prompt_ids, n_new_tokens)
    if empty is not None:
        return empty, np.zeros((ids.shape[0],), np.float32)
    n_batch, b = ids.shape[0], int(beam_size)

    from deeplearning4j_tpu.nn import helpers as _helpers
    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer

    inp = net.conf.inputs[0]
    out_name = net.conf.outputs[0]
    key = ("beam", n_new_tokens, b, eos_id, float(length_penalty),
           _helpers.version())
    if key not in net._jit_cache:
        net._evict_stale(_helpers.version())
        dtype = net.conf.global_conf.jnp_dtype()

        def gather_beams(carries, flat_idx, nb):
            # reindex batch-leading carry leaves; scalars (positions) pass
            return jax.tree_util.tree_map(
                lambda a: a[flat_idx] if (hasattr(a, "ndim") and a.ndim >= 1
                                          and a.shape[0] == nb) else a,
                carries)

        def select(scores, finished, logp, n, v):
            """Top-b continuations over beam*vocab."""
            if eos_id is not None:
                cont = jnp.full((v,), -1e30).at[eos_id].set(0.0)
                logp = jnp.where(finished[..., None], cont, logp)
            total = scores[..., None] + logp            # [N, B, V]
            new_scores, flat = jax.lax.top_k(total.reshape(n, b * v), b)
            beam_idx = flat // v                         # [N, B]
            tok = (flat % v).astype(jnp.int32)
            return new_scores, beam_idx, tok

        def fn(params, states, prompt):
            n, t0 = prompt.shape
            nb = n * b
            # prefill ONCE per batch row; beams split only after the prompt
            carries = {vd.name: vd.obj.init_carry(n, dtype)
                       for vd in net.conf.layer_vertices()
                       if isinstance(vd.obj, BaseRecurrentLayer)}
            acts, _, _, carries = net._forward_all(
                params, states, {inp: prompt}, train=False, rng=None,
                carries=carries)
            logp = jnp.log(jnp.maximum(acts[out_name][:, -1], 1e-20))
            v = logp.shape[-1]
            # replicate the prompt's caches across the beam axis
            carries = jax.tree_util.tree_map(
                lambda a: jnp.repeat(a, b, axis=0)
                if (hasattr(a, "ndim") and a.ndim >= 1 and a.shape[0] == n)
                else a, carries)
            # first selection: top-b distinct tokens straight from the
            # prompt distribution (all beams would be identical anyway)
            scores, tok = jax.lax.top_k(logp.astype(jnp.float32), b)
            tok = tok.astype(jnp.int32)                  # [N, B]
            finished = (tok == eos_id) if eos_id is not None \
                else jnp.zeros((n, b), bool)
            row = jnp.arange(n)[:, None] * b
            toks = jnp.zeros((n, b, n_new_tokens), jnp.int32)
            toks = toks.at[:, :, 0].set(tok)
            use_len = bool(length_penalty > 0)
            # tokens before/incl. EOS; scalar placeholder keeps the carry
            # structure stable when normalization is off (no dead gathers)
            length = (jnp.ones((n, b), jnp.float32) if use_len
                      else jnp.zeros(()))

            def step(carry, i):
                carries, toks, scores, finished, length, last = carry
                x = last.reshape(nb)[:, None, None].astype(dtype)
                acts, _, _, carries = net._forward_all(
                    params, states, {inp: x}, train=False, rng=None,
                    carries=carries)
                logp = jnp.log(jnp.maximum(acts[out_name][:, -1], 1e-20))
                logp = logp.reshape(n, b, v).astype(jnp.float32)
                scores, beam_idx, tok = select(scores, finished, logp, n, v)
                flat_idx = (row + beam_idx).reshape(-1)
                carries = gather_beams(carries, flat_idx, nb)
                toks = jnp.take_along_axis(toks, beam_idx[:, :, None], axis=1)
                finished = jnp.take_along_axis(finished, beam_idx, axis=1)
                if use_len:
                    length = jnp.take_along_axis(length, beam_idx, axis=1)
                    length = jnp.where(finished, length, length + 1.0)
                toks = jax.lax.dynamic_update_index_in_dim(
                    toks, tok, i, axis=2)
                if eos_id is not None:
                    finished = finished | (tok == eos_id)
                return (carries, toks, scores, finished, length, tok), None

            (carries, toks, scores, finished, length, _), _ = jax.lax.scan(
                step, (carries, toks, scores, finished, length, tok),
                jnp.arange(1, n_new_tokens))
            if use_len:
                scores = scores / jnp.maximum(length, 1.0) ** length_penalty
            best = jnp.argmax(scores, axis=1)
            return (jnp.take_along_axis(
                        toks, best[:, None, None], axis=1)[:, 0],
                    jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0])

        net._jit_cache[key] = jax.jit(fn)
    toks, scores = net._jit_cache[key](net.params, net.states,
                                       jnp.asarray(ids, jnp.float32))
    return np.asarray(toks).astype(np.int64), np.asarray(scores)


def _require_graph(net, fn_name: str) -> None:
    """The compiled decode paths drive ComputationGraph internals
    (conf.vertices / conf.layer_vertices / conf.inputs); fail with a clear
    message instead of an AttributeError deep inside for other net types
    (the host-loop :func:`generate` handles MultiLayerNetwork)."""
    conf = getattr(net, "conf", None)
    if not (hasattr(conf, "vertices") and hasattr(conf, "inputs")):
        raise TypeError(
            f"{fn_name} requires a ComputationGraph-based network "
            f"(e.g. TransformerLM.build()); got {type(net).__name__}. "
            "Use generate() for MultiLayerNetwork models.")


def _prep_prompt(net, prompt_ids, n_new_tokens: int):
    """Shared generate prologue: normalize the prompt to [N,T], early-out
    for n_new_tokens<=0, and reject sequences the decode caches cannot hold.
    Returns (ids, empty_result_or_None)."""
    import numpy as np

    ids = np.asarray(prompt_ids)
    if ids.ndim == 1:
        ids = ids[None]
    if n_new_tokens <= 0:
        return ids, np.zeros((ids.shape[0], 0), np.int64)
    cap = _kv_capacity(net)
    total = ids.shape[1] + n_new_tokens - 1  # last token is never fed back
    if cap is not None and total > cap:
        raise ValueError(
            f"prompt ({ids.shape[1]}) + {n_new_tokens} new tokens needs "
            f"{total} cache slots but the model holds {cap} "
            f"(max_length/max_cache)")
    return ids, None


def _kv_capacity(net):
    """Smallest stateful-decode capacity across the net's layers (KV caches
    and positional tables), or None if the net has none."""
    from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer

    layer_vertices = getattr(net.conf, "layer_vertices", None)
    layers = ([vd.obj for vd in layer_vertices()] if layer_vertices
              else getattr(net, "layers", []))
    caps = [obj.carry_capacity() for obj in layers
            if isinstance(obj, BaseRecurrentLayer)
            and obj.carry_capacity() is not None]
    return min(caps) if caps else None


def transformer_encoder_block(g, name: str, src: str, d_model: int,
                              n_heads: int, d_ff: int,
                              attn_dropout: float = 0.0) -> str:
    """One pre-activation-free (post-LN, BERT-style) encoder block as graph
    vertices: self-attention + residual + LayerNorm, position-wise FFN +
    residual + LayerNorm. Returns the output vertex name."""
    from deeplearning4j_tpu.nn.layers import (
        LayerNormalizationLayer,
        SelfAttentionLayer,
    )
    from deeplearning4j_tpu.nn.vertices import ElementWiseVertex

    g.add_layer(f"{name}-att",
                SelfAttentionLayer(n_heads=n_heads,
                                   head_size=d_model // n_heads,
                                   project_input=True,
                                   attn_dropout=attn_dropout), src)
    g.add_vertex(f"{name}-res1", ElementWiseVertex(op="add"),
                 src, f"{name}-att")
    g.add_layer(f"{name}-ln1", LayerNormalizationLayer(), f"{name}-res1")
    g.add_layer(f"{name}-ff1", DenseLayer(n_in=d_model, n_out=d_ff,
                                          activation="gelu"), f"{name}-ln1")
    g.add_layer(f"{name}-ff2", DenseLayer(n_in=d_ff, n_out=d_model,
                                          activation="identity"),
                f"{name}-ff1")
    g.add_vertex(f"{name}-res2", ElementWiseVertex(op="add"),
                 f"{name}-ln1", f"{name}-ff2")
    g.add_layer(f"{name}-ln2", LayerNormalizationLayer(), f"{name}-res2")
    return f"{name}-ln2"


@register_zoo_model
class TransformerEncoder(ZooModel):
    """BERT-base-shape transformer encoder for sequence classification
    (no reference counterpart — the snapshot predates attention; this is the
    framework-native builder behind the BASELINE "BERT-base" config, whose
    import path lives in ``modelimport/keras``).

    Defaults are BERT-base: 12 layers, d_model 768, 12 heads, d_ff 3072.
    Token ids [N,T] → embeddings + learned positions → N encoder blocks →
    mean-pool → classifier.
    """

    def __init__(self, num_labels: int = 2, seed: int = 123,
                 vocab_size: int = 30522, max_length: int = 128,
                 n_layers: int = 12, d_model: int = 768, n_heads: int = 12,
                 d_ff: int = 3072):
        super().__init__(num_labels, seed)
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.n_layers = n_layers
        self.d_model = d_model
        self.n_heads = n_heads
        self.d_ff = d_ff

    def meta_data(self):
        return ModelMetaData(((self.max_length,),), 1, "rnn")

    def conf(self):
        from deeplearning4j_tpu.nn.layers import (
            EmbeddingSequenceLayer,
            GlobalPoolingLayer,
            PositionalEmbeddingLayer,
        )

        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .weight_init("xavier").updater(Adam(1e-4)).graph_builder()
             .add_inputs("tokens")
             .set_input_types(InputType.recurrent(1, self.max_length)))
        g.add_layer("embed",
                    EmbeddingSequenceLayer(n_in=self.vocab_size,
                                           n_out=self.d_model), "tokens")
        g.add_layer("pos", PositionalEmbeddingLayer(n_in=self.d_model,
                                                    max_len=self.max_length),
                    "embed")
        src = "pos"
        for i in range(self.n_layers):
            src = transformer_encoder_block(g, f"block{i}", src,
                                            self.d_model, self.n_heads,
                                            self.d_ff)
        g.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), src)
        g.add_layer("out", OutputLayer(n_in=self.d_model,
                                       n_out=self.num_labels,
                                       activation="softmax", loss="mcxent"),
                    "pool")
        g.set_outputs("out")
        return g.build()


@register_zoo_model
class VisionTransformer(ZooModel):
    """ViT (Dosovitskiy et al. 2020) — patchify-and-attend image
    classifier (no reference counterpart; the conv+attention composition
    the snapshot-era zoo could not express, built entirely from this
    framework's vertices).

    Images [N,H,W,C] → non-overlapping patch embedding (Conv2D with
    kernel == stride == patch) → [N, T=HW/p², d_model] token sequence →
    learned positions → encoder blocks (the TransformerEncoder blocks)
    → mean-pool → classifier. Defaults are ViT-Tiny-ish for trainability
    at test scale; pass ViT-B/16 numbers (12 layers, d_model 768,
    12 heads, d_ff 3072, patch 16, image 224) for the paper shape.
    """

    def __init__(self, num_labels: int = 10, seed: int = 123,
                 image_size: int = 32, channels: int = 3,
                 patch_size: int = 4, n_layers: int = 4,
                 d_model: int = 64, n_heads: int = 4, d_ff: int = 128):
        super().__init__(num_labels, seed)
        if image_size % patch_size != 0:
            raise ValueError(
                f"image_size {image_size} not divisible by patch_size "
                f"{patch_size}")
        self.image_size = image_size
        self.channels = channels
        self.patch_size = patch_size
        self.n_layers = n_layers
        self.d_model = d_model
        self.n_heads = n_heads
        self.d_ff = d_ff

    @property
    def num_patches(self) -> int:
        side = self.image_size // self.patch_size
        return side * side

    def meta_data(self):
        return ModelMetaData(
            ((self.channels, self.image_size, self.image_size),), 1, "cnn")

    def conf(self):
        from deeplearning4j_tpu.nn.layers import (
            GlobalPoolingLayer,
            PositionalEmbeddingLayer,
        )
        from deeplearning4j_tpu.nn.vertices import ReshapeVertex

        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .weight_init("xavier").updater(Adam(3e-4)).graph_builder()
             .add_inputs("image")
             .set_input_types(InputType.convolutional(
                 self.image_size, self.image_size, self.channels)))
        # one conv with kernel == stride IS the patch embedding: each
        # patch hits the MXU as a single [p*p*C, d_model] matmul
        g.add_layer("patch",
                    ConvolutionLayer(n_out=self.d_model,
                                     kernel_size=(self.patch_size,
                                                  self.patch_size),
                                     stride=(self.patch_size,
                                             self.patch_size),
                                     activation="identity"), "image")
        g.add_vertex("tokens",
                     ReshapeVertex(shape=(self.num_patches, self.d_model)),
                     "patch")
        g.add_layer("pos",
                    PositionalEmbeddingLayer(n_in=self.d_model,
                                             max_len=self.num_patches),
                    "tokens")
        src = "pos"
        for i in range(self.n_layers):
            src = transformer_encoder_block(g, f"block{i}", src,
                                            self.d_model, self.n_heads,
                                            self.d_ff)
        g.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), src)
        g.add_layer("out", OutputLayer(n_in=self.d_model,
                                       n_out=self.num_labels,
                                       activation="softmax", loss="mcxent"),
                    "pool")
        g.set_outputs("out")
        return g.build()
