"""Model zoo: 13 instantiable architectures + selector + pretrained loading.

Reference: ``deeplearning4j-zoo/`` (``ZooModel.java``, ``ModelSelector.java``,
13 models under ``zoo/model/``).
"""

from deeplearning4j_tpu.zoo.zoo_model import (
    ModelMetaData,
    ModelSelector,
    PretrainedType,
    ZooModel,
    register_zoo_model,
)
from deeplearning4j_tpu.zoo.models import (
    AlexNet,
    Darknet19,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    LeNet,
    ResNet50,
    SimpleCNN,
    TextGenerationLSTM,
    TinyYOLO,
    TransformerEncoder,
    VisionTransformer,
    TransformerLM,
    VGG16,
    VGG19,
    YOLO2,
    beam_search,
    generate,
    generate_on_device,
    lm_labels,
)

__all__ = [
    "ModelMetaData", "ModelSelector", "PretrainedType", "ZooModel",
    "register_zoo_model",
    "AlexNet", "Darknet19", "FaceNetNN4Small2", "GoogLeNet",
    "InceptionResNetV1", "LeNet", "ResNet50", "SimpleCNN",
    "TextGenerationLSTM", "TinyYOLO", "TransformerEncoder", "TransformerLM",
    "VisionTransformer",
    "VGG16", "VGG19", "YOLO2", "beam_search", "generate",
    "generate_on_device", "lm_labels",
]
from deeplearning4j_tpu.zoo.labels import (  # noqa: F401
    ClassPrediction,
    COCOLabels,
    ImageNetLabels,
    Labels,
    VOCLabels,
)
