"""Class-label helpers for zoo models.

Reference: ``deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/util/``
— ``Labels``/``BaseLabels`` (download + parse a label file, ``decodePredictions``),
``imagenet/ImageNetLabels.java``, ``darknet/VOCLabels.java``,
``darknet/COCOLabels.java``, ``darknet/DarknetLabels.java``.

TPU-native differences: the 20-class VOC and 80-class COCO vocabularies are
small, stable, public data and are vendored directly; ImageNet's 1000-class
table (which the reference downloads at runtime) loads from a local file —
``$DL4J_TPU_ZOO_DIR/imagenet_class_index.json`` (the standard Keras-format
index) or a path you pass — since this environment has no egress.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

VOC_CLASSES: Tuple[str, ...] = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor")

COCO_CLASSES: Tuple[str, ...] = (
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep", "cow",
    "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella",
    "handbag", "tie", "suitcase", "frisbee", "skis", "snowboard",
    "sports ball", "kite", "baseball bat", "baseball glove", "skateboard",
    "surfboard", "tennis racket", "bottle", "wine glass", "cup", "fork",
    "knife", "spoon", "bowl", "banana", "apple", "sandwich", "orange",
    "broccoli", "carrot", "hot dog", "pizza", "donut", "cake", "chair",
    "couch", "potted plant", "bed", "dining table", "toilet", "tv",
    "laptop", "mouse", "remote", "keyboard", "cell phone", "microwave",
    "oven", "toaster", "sink", "refrigerator", "book", "clock", "vase",
    "scissors", "teddy bear", "hair drier", "toothbrush")


class ClassPrediction:
    """One decoded prediction (``zoo/util/ClassPrediction.java``)."""

    def __init__(self, number: int, label: str, probability: float):
        self.number = int(number)
        self.label = label
        self.probability = float(probability)

    def __repr__(self):
        return (f"ClassPrediction(number={self.number}, "
                f"label={self.label!r}, probability={self.probability:.4f})")


class Labels:
    """Label-table SPI (``zoo/util/Labels.java``): index → name plus
    ``decode_predictions`` over a batch of output probabilities."""

    def __init__(self, labels: Sequence[str]):
        self._labels = list(labels)

    def get_label(self, n: int) -> str:
        return self._labels[n]

    def __len__(self) -> int:
        return len(self._labels)

    def decode_predictions(self, predictions, top: int = 5
                           ) -> List[List[ClassPrediction]]:
        """Top-``top`` (label, probability) per example
        (``BaseLabels.decodePredictions``). ``predictions`` is [N, C]."""
        p = np.asarray(predictions)
        if p.ndim == 1:
            p = p[None, :]
        if p.shape[1] != len(self._labels):
            raise ValueError(
                f"predictions have {p.shape[1]} classes but the label "
                f"table has {len(self._labels)}")
        out = []
        for row in p:
            idx = np.argsort(-row)[:top]
            out.append([ClassPrediction(int(i), self._labels[int(i)],
                                        float(row[int(i)])) for i in idx])
        return out


class VOCLabels(Labels):
    """Pascal VOC's 20 classes (``darknet/VOCLabels.java``) — the label set
    TinyYOLO was trained on."""

    def __init__(self):
        super().__init__(VOC_CLASSES)


class COCOLabels(Labels):
    """COCO's 80 classes (``darknet/COCOLabels.java``) — the label set
    YOLO2 was trained on."""

    def __init__(self):
        super().__init__(COCO_CLASSES)


class ImageNetLabels(Labels):
    """ImageNet-1k labels (``imagenet/ImageNetLabels.java``). The reference
    downloads its table at runtime; here it loads the standard Keras-format
    ``imagenet_class_index.json`` (``{"0": ["n01440764", "tench"], ...}``)
    from ``path``, or ``$DL4J_TPU_ZOO_DIR/imagenet_class_index.json``."""

    def __init__(self, path: Optional[str] = None):
        if path is None:
            root = os.environ.get(
                "DL4J_TPU_ZOO_DIR",
                os.path.expanduser("~/.deeplearning4j_tpu/zoo"))
            path = os.path.join(root, "imagenet_class_index.json")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"No ImageNet label table at {path}; place the standard "
                "imagenet_class_index.json there (the reference downloads "
                "the same table at runtime)")
        with open(path, "r", encoding="utf-8") as fh:
            idx = json.load(fh)
        labels = [idx[str(i)][1] for i in range(len(idx))]
        super().__init__(labels)
