"""ROC / AUC evaluation (DL4J ``eval/ROC.java``, ``ROCBinary``, ``ROCMultiClass``).

Two modes, matching ``ROC.java:61-85``:

- ``threshold_steps=0`` (default): EXACT mode — scores are retained and
  AUROC/AUPRC computed by sorting (threshold-free).
- ``threshold_steps=N > 0``: BINNED mode — fixed thresholds ``i/N`` for
  ``i in 0..N``; only (TP, FP) counts per threshold plus the actual
  positive/negative totals are kept. This is the mode built for batched /
  distributed evaluation: state is O(N) regardless of dataset size and
  ``merge`` is count addition, so shards evaluate independently and merge
  without ever holding the score set in one host's memory. (Reference
  caveat applies: with very skewed score distributions the thresholded
  approach can underestimate the true area.)

Counting semantics match the reference's CompareAndSet pair
(``ROC.java:268-280``): predicted-positive at threshold t iff score >= t,
except at t == 1.0 where nothing is predicted positive — giving the curve
its (0,0) endpoint.
"""

from __future__ import annotations

import json
from typing import Optional, Tuple

import numpy as np

_trapezoid = getattr(np, "trapezoid", None) or np.trapz


def _auc_roc(labels: np.ndarray, scores: np.ndarray) -> float:
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0
    order = np.argsort(scores)
    ranks = np.empty(len(scores), dtype=np.float64)
    # average ranks for ties
    sorted_scores = scores[order]
    ranks[order] = np.arange(1, len(scores) + 1)
    i = 0
    while i < len(scores):
        j = i
        while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            avg = (i + j) / 2 + 1
            ranks[order[i:j + 1]] = avg
        i = j + 1
    sum_pos_ranks = ranks[pos].sum()
    return float((sum_pos_ranks - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def _auc_pr(labels: np.ndarray, scores: np.ndarray) -> float:
    order = np.argsort(-scores)
    l = labels[order] > 0.5
    tp = np.cumsum(l)
    fp = np.cumsum(~l)
    n_pos = int(l.sum())
    if n_pos == 0:
        return 0.0
    precision = tp / np.maximum(tp + fp, 1)
    recall = tp / n_pos
    # step-wise integration
    prev_r = 0.0
    area = 0.0
    for p, r in zip(precision, recall):
        area += p * (r - prev_r)
        prev_r = r
    return float(area)


class ROC:
    """Binary ROC: labels [N] or [N,2] (prob of class 1 scored)."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self.is_exact = self.threshold_steps <= 0
        self.labels = []
        self.scores = []
        if not self.is_exact:
            n = self.threshold_steps
            self.thresholds = np.arange(n + 1, dtype=np.float64) / n
            self.tp_counts = np.zeros(n + 1, np.int64)
            self.fp_counts = np.zeros(n + 1, np.int64)
            self.count_actual_positive = 0
            self.count_actual_negative = 0

    def _eval_binned(self, labels: np.ndarray, scores: np.ndarray) -> None:
        pos = labels > 0.5
        self.count_actual_positive += int(pos.sum())
        self.count_actual_negative += int((~pos).sum())
        n = self.threshold_steps
        # O(N + steps): histogram scores into [i/n, (i+1)/n) bins, then
        # #(score >= i/n) is a reverse cumulative sum — score == i/n lands
        # in bin i, so the >= boundary semantics are exact.
        # +1e-9: keep a score EXACTLY at a threshold on the >= side despite
        # float rounding in scores * n (e.g. 0.3 * 10 == 2.9999999999999996)
        bins = np.clip(np.floor(scores * n + 1e-9).astype(np.int64), 0, n)
        pos_hist = np.bincount(bins[pos], minlength=n + 1)
        neg_hist = np.bincount(bins[~pos], minlength=n + 1)
        at_least = lambda h: np.cumsum(h[::-1])[::-1]
        tp, fp = at_least(pos_hist), at_least(neg_hist)
        tp[-1] = 0  # ROC.java:268 CompareAndSet pair: nothing passes t=1.0
        fp[-1] = 0
        self.tp_counts += tp
        self.fp_counts += fp

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            # time series [N,T,C]: flatten time; a [N,T] mask selects steps
            n, t, c = labels.shape
            labels = labels.reshape(n * t, c)
            predictions = predictions.reshape(n * t, -1)
            if mask is not None:
                m = np.asarray(mask).astype(bool)
                if m.shape != (n, t):
                    raise ValueError(
                        f"time-series ROC mask must be [N,T]; got {m.shape}")
                mask = m.reshape(n * t)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        elif labels.ndim == 2 and labels.shape[1] == 1:
            labels = labels[:, 0]
            predictions = predictions[:, 0]
        elif labels.ndim == 1 and predictions.ndim == 2:
            # 1-D class labels with [N, 2] probabilities: score class 1
            if predictions.shape[1] != 2:
                raise ValueError(
                    f"ROC is binary: got 1-D labels with [N, "
                    f"{predictions.shape[1]}] predictions (use ROCMultiClass)")
            predictions = predictions[:, -1]
        if mask is not None:
            m = np.asarray(mask).astype(bool).ravel()
            labels, predictions = labels[m], predictions[m]
        if self.is_exact:
            self.labels.append(labels.ravel())
            self.scores.append(predictions.ravel())
        else:
            self._eval_binned(labels.ravel(), predictions.ravel())

    def stats(self) -> str:
        """``ROC.stats()``: "AUC: [x]"."""
        return f"AUC: [{self.calculate_auc():.6f}]"

    # ---------------------------------------------------------------- curves
    def get_roc_curve(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(thresholds, fpr, tpr). Binned mode: one point per fixed
        threshold (``ROC.java getRocCurve``); exact mode: one point per
        distinct score."""
        if not self.is_exact:
            p = max(self.count_actual_positive, 1)
            n = max(self.count_actual_negative, 1)
            return (self.thresholds.copy(), self.fp_counts / n,
                    self.tp_counts / p)
        labels = np.concatenate(self.labels)
        scores = np.concatenate(self.scores)
        order = np.argsort(-scores)
        l = labels[order] > 0.5
        tp = np.cumsum(l)
        fp = np.cumsum(~l)
        n_pos, n_neg = max(int(l.sum()), 1), max(int((~l).sum()), 1)
        return (scores[order], fp / n_neg, tp / n_pos)

    def get_precision_recall_curve(self
                                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(thresholds, precision, recall) — ``getPrecisionRecallCurve``."""
        if not self.is_exact:
            p = max(self.count_actual_positive, 1)
            pred_pos = self.tp_counts + self.fp_counts
            precision = np.where(pred_pos > 0, self.tp_counts
                                 / np.maximum(pred_pos, 1), 1.0)
            return (self.thresholds.copy(), precision, self.tp_counts / p)
        labels = np.concatenate(self.labels)
        scores = np.concatenate(self.scores)
        order = np.argsort(-scores)
        l = labels[order] > 0.5
        tp = np.cumsum(l)
        fp = np.cumsum(~l)
        n_pos = max(int(l.sum()), 1)
        return (scores[order], tp / np.maximum(tp + fp, 1), tp / n_pos)

    def calculate_auc(self) -> float:
        if self.is_exact:
            return _auc_roc(np.concatenate(self.labels),
                            np.concatenate(self.scores))
        _, fpr, tpr = self.get_roc_curve()
        # thresholds ascend → (fpr, tpr) descend from (1,1) to (0,0)
        return float(_trapezoid(tpr[::-1], fpr[::-1]))

    def calculate_auc_pr(self) -> float:
        if self.is_exact:
            return _auc_pr(np.concatenate(self.labels),
                           np.concatenate(self.scores))
        _, precision, recall = self.get_precision_recall_curve()
        r, p = recall[::-1], precision[::-1]  # recall ascending
        return float(_trapezoid(p, r))

    # ----------------------------------------------------------- merge/serde
    def merge(self, other: "ROC") -> "ROC":
        """Distributed merge (``BaseEvaluation.merge``): count addition in
        binned mode (O(steps) state), score concatenation in exact mode."""
        if self.is_exact != other.is_exact or (
                not self.is_exact
                and self.threshold_steps != other.threshold_steps):
            raise ValueError(
                "cannot merge ROC instances with different threshold_steps "
                f"({self.threshold_steps} vs {other.threshold_steps})")
        if self.is_exact:
            self.labels.extend(other.labels)
            self.scores.extend(other.scores)
        else:
            self.tp_counts += other.tp_counts
            self.fp_counts += other.fp_counts
            self.count_actual_positive += other.count_actual_positive
            self.count_actual_negative += other.count_actual_negative
        return self

    def to_json(self) -> str:
        if self.is_exact:
            raise ValueError("exact-mode ROC state is the raw score set; "
                             "use threshold_steps > 0 for compact "
                             "serializable/mergeable state")
        return json.dumps({
            "threshold_steps": self.threshold_steps,
            "tp_counts": self.tp_counts.tolist(),
            "fp_counts": self.fp_counts.tolist(),
            "count_actual_positive": self.count_actual_positive,
            "count_actual_negative": self.count_actual_negative,
        })

    @staticmethod
    def from_json(s: str) -> "ROC":
        d = json.loads(s)
        r = ROC(threshold_steps=d["threshold_steps"])
        r.tp_counts = np.asarray(d["tp_counts"], np.int64)
        r.fp_counts = np.asarray(d["fp_counts"], np.int64)
        r.count_actual_positive = d["count_actual_positive"]
        r.count_actual_negative = d["count_actual_negative"]
        return r


class ROCBinary:
    """Per-output binary ROC for multi-label outputs [N, C]."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self.is_exact = self.threshold_steps <= 0
        self.labels = []
        self.scores = []
        self.masks = []  # per-batch [N, C] masks (or None), exact mode
        self._per_col = {}  # col -> binned ROC (ROCBinary.java mode)

    def _col_roc(self, col: int) -> "ROC":
        if col not in self._per_col:
            self._per_col[col] = ROC(threshold_steps=self.threshold_steps)
        return self._per_col[col]

    def num_labels(self) -> int:
        """Number of output columns seen so far (``numLabels``)."""
        if self._per_col:
            return max(self._per_col) + 1
        if self.labels:
            return int(np.asarray(self.labels[0]).shape[-1])
        return 0

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            # time series [N,T,C]: flatten time; a [N,T] mask selects rows
            n, t, c = labels.shape
            labels = labels.reshape(n * t, c)
            predictions = predictions.reshape(n * t, -1)
            if mask is not None:
                m = np.asarray(mask).astype(bool)
                if m.shape == (n, t):
                    keep = m.reshape(n * t)
                    labels, predictions = labels[keep], predictions[keep]
                    mask = None
                else:  # [N,T,C] per-output mask
                    mask = m.reshape(n * t, c)
        m2 = None  # [N, C] per-output mask (ROCBinary.java supports both)
        if mask is not None:
            m = np.asarray(mask).astype(bool)
            if m.ndim == 2:
                m2 = m
            else:
                m = m.ravel()
                labels, predictions = labels[m], predictions[m]
        if self.is_exact:
            self.labels.append(labels)
            self.scores.append(predictions)
            self.masks.append(m2)
        else:
            for col in range(labels.shape[1]):
                keep = slice(None) if m2 is None else m2[:, col]
                self._col_roc(col).eval(labels[keep, col],
                                        predictions[keep, col])

    def calculate_auc(self, col: int) -> float:
        if not self.is_exact:
            return self._col_roc(col).calculate_auc()
        l = np.concatenate(self.labels)[:, col]
        s = np.concatenate(self.scores)[:, col]
        ms = [np.ones(len(lb), bool) if mk is None else mk[:, col]
              for lb, mk in zip(self.labels, self.masks)]
        keep = np.concatenate(ms)
        return _auc_roc(l[keep], s[keep])

    def get_roc_curve(self, col: int):
        """(thresholds, fpr, tpr) for one output column
        (``ROCBinary.getRocCurve``)."""
        return self._single(col).get_roc_curve()

    def get_precision_recall_curve(self, col: int):
        return self._single(col).get_precision_recall_curve()

    def _single(self, col: int) -> "ROC":
        if not self.is_exact:
            return self._col_roc(col)
        r = ROC()
        for lb, sc, mk in zip(self.labels, self.scores, self.masks):
            keep = slice(None) if mk is None else mk[:, col]
            r.eval(lb[keep, col], sc[keep, col])
        return r

    def merge(self, other: "ROCBinary") -> "ROCBinary":
        if self.is_exact != other.is_exact:
            raise ValueError("cannot merge exact with binned ROCBinary")
        if self.is_exact:
            self.labels.extend(other.labels)
            self.scores.extend(other.scores)
            self.masks.extend(other.masks)
        else:
            for col, r in other._per_col.items():
                self._col_roc(col).merge(r)
        return self


class ROCMultiClass:
    """One-vs-all ROC per class for softmax outputs [N, C]."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self.is_exact = self.threshold_steps <= 0
        self.labels = []
        self.scores = []
        self._per_cls = {}

    def _cls_roc(self, cls: int) -> "ROC":
        if cls not in self._per_cls:
            self._per_cls[cls] = ROC(threshold_steps=self.threshold_steps)
        return self._per_cls[cls]

    def num_classes(self) -> int:
        """Number of classes seen so far."""
        if self._per_cls:
            return max(self._per_cls) + 1
        if self.scores:
            return int(np.asarray(self.scores[0]).shape[-1])
        return 0

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3 or predictions.ndim == 3:
            # time series [N,T,C]: flatten time; a [N,T] mask selects steps
            n, t = predictions.shape[:2]
            predictions = predictions.reshape(n * t, -1)
            labels = (labels.reshape(n * t, -1) if labels.ndim == 3
                      else labels.reshape(n * t))
            if mask is not None:
                m = np.asarray(mask).astype(bool)
                if m.shape != (n, t):
                    raise ValueError(
                        f"time-series ROCMultiClass mask must be [N,T]; got "
                        f"{m.shape}")
                mask = m.reshape(n * t)
        if mask is not None:
            # one-vs-all over softmax outputs: a mask is per-EXAMPLE; a 2-D
            # [N, 1] column is accepted and flattened
            m = np.asarray(mask).astype(bool)
            if m.ndim == 2 and m.shape[1] != 1:
                raise ValueError(
                    "ROCMultiClass masks are per-example ([N] or [N,1]); "
                    f"got shape {m.shape}")
            m = m.ravel()
            labels, predictions = labels[m], predictions[m]
        if self.is_exact:
            self.labels.append(labels)
            self.scores.append(predictions)
            return
        for cls in range(predictions.shape[1]):
            binary = (labels[:, cls] if labels.ndim == 2
                      else (labels == cls).astype(np.float64))
            self._cls_roc(cls).eval(binary, predictions[:, cls])

    def calculate_auc(self, cls: int) -> float:
        if not self.is_exact:
            return self._cls_roc(cls).calculate_auc()
        l = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        if l.ndim == 2:
            binary = l[:, cls]
        else:
            binary = (l == cls).astype(np.float64)
        return _auc_roc(binary, s[:, cls])

    def get_roc_curve(self, cls: int):
        """(thresholds, fpr, tpr) one-vs-all for one class
        (``ROCMultiClass.getRocCurve``)."""
        return self._single(cls).get_roc_curve()

    def get_precision_recall_curve(self, cls: int):
        return self._single(cls).get_precision_recall_curve()

    def _single(self, cls: int) -> "ROC":
        if not self.is_exact:
            return self._cls_roc(cls)
        r = ROC()
        for lb, sc in zip(self.labels, self.scores):
            binary = (lb[:, cls] if lb.ndim == 2
                      else (lb == cls).astype(np.float64))
            r.eval(binary, sc[:, cls])
        return r

    def merge(self, other: "ROCMultiClass") -> "ROCMultiClass":
        if self.is_exact != other.is_exact:
            raise ValueError("cannot merge exact with binned ROCMultiClass")
        if self.is_exact:
            self.labels.extend(other.labels)
            self.scores.extend(other.scores)
        else:
            for cls, r in other._per_cls.items():
                self._cls_roc(cls).merge(r)
        return self
