"""ROC / AUC evaluation (DL4J ``eval/ROC.java``, ``ROCBinary``, ``ROCMultiClass``).

Exact (threshold-free) AUROC/AUPRC via sorting, equivalent to DL4J's
``thresholdSteps=0`` exact mode.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _auc_roc(labels: np.ndarray, scores: np.ndarray) -> float:
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0
    order = np.argsort(scores)
    ranks = np.empty(len(scores), dtype=np.float64)
    # average ranks for ties
    sorted_scores = scores[order]
    ranks[order] = np.arange(1, len(scores) + 1)
    i = 0
    while i < len(scores):
        j = i
        while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            avg = (i + j) / 2 + 1
            ranks[order[i:j + 1]] = avg
        i = j + 1
    sum_pos_ranks = ranks[pos].sum()
    return float((sum_pos_ranks - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def _auc_pr(labels: np.ndarray, scores: np.ndarray) -> float:
    order = np.argsort(-scores)
    l = labels[order] > 0.5
    tp = np.cumsum(l)
    fp = np.cumsum(~l)
    n_pos = int(l.sum())
    if n_pos == 0:
        return 0.0
    precision = tp / np.maximum(tp + fp, 1)
    recall = tp / n_pos
    # step-wise integration
    prev_r = 0.0
    area = 0.0
    for p, r in zip(precision, recall):
        area += p * (r - prev_r)
        prev_r = r
    return float(area)


class ROC:
    """Binary ROC: labels [N] or [N,2] (prob of class 1 scored)."""

    def __init__(self):
        self.labels = []
        self.scores = []

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        elif labels.ndim == 2 and labels.shape[1] == 1:
            labels = labels[:, 0]
            predictions = predictions[:, 0]
        elif labels.ndim == 1 and predictions.ndim == 2:
            # 1-D class labels with [N, 2] probabilities: score class 1
            if predictions.shape[1] != 2:
                raise ValueError(
                    f"ROC is binary: got 1-D labels with [N, "
                    f"{predictions.shape[1]}] predictions (use ROCMultiClass)")
            predictions = predictions[:, -1]
        if mask is not None:
            m = np.asarray(mask).astype(bool).ravel()
            labels, predictions = labels[m], predictions[m]
        self.labels.append(labels.ravel())
        self.scores.append(predictions.ravel())

    def calculate_auc(self) -> float:
        return _auc_roc(np.concatenate(self.labels), np.concatenate(self.scores))

    def calculate_auc_pr(self) -> float:
        return _auc_pr(np.concatenate(self.labels), np.concatenate(self.scores))


class ROCBinary:
    """Per-output binary ROC for multi-label outputs [N, C]."""

    def __init__(self):
        self.labels = []
        self.scores = []

    def eval(self, labels, predictions, mask=None) -> None:
        self.labels.append(np.asarray(labels, np.float64))
        self.scores.append(np.asarray(predictions, np.float64))

    def calculate_auc(self, col: int) -> float:
        l = np.concatenate(self.labels)[:, col]
        s = np.concatenate(self.scores)[:, col]
        return _auc_roc(l, s)


class ROCMultiClass:
    """One-vs-all ROC per class for softmax outputs [N, C]."""

    def __init__(self):
        self.labels = []
        self.scores = []

    def eval(self, labels, predictions, mask=None) -> None:
        self.labels.append(np.asarray(labels, np.float64))
        self.scores.append(np.asarray(predictions, np.float64))

    def calculate_auc(self, cls: int) -> float:
        l = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        if l.ndim == 2:
            binary = l[:, cls]
        else:
            binary = (l == cls).astype(np.float64)
        return _auc_roc(binary, s[:, cls])
