"""Calibration evaluation (DL4J ``eval/EvaluationCalibration.java``).

Full reference depth: PER-CLASS reliability diagrams
(``getReliabilityDiagram(classIdx)``), per-class residual plots
(``getResidualPlot``) and probability histograms
(``getProbabilityHistogram``), overall variants, label/prediction class
counts, merge/reset — computed with the same bin semantics (last bin closed
at 1.0, positive-label rows selected by the label matrix, per-example or
per-output masks). Plus ``expected_calibration_error`` as the summary
scalar the dashboard panel plots.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class Histogram:
    """``org.deeplearning4j.eval.curves.Histogram`` counterpart."""

    title: str
    lower: float
    upper: float
    counts: np.ndarray

    @property
    def bin_edges(self) -> np.ndarray:
        return np.linspace(self.lower, self.upper, len(self.counts) + 1)

    def to_dict(self) -> dict:
        return {"title": self.title, "lower": self.lower, "upper": self.upper,
                "counts": [int(c) for c in self.counts]}


@dataclasses.dataclass
class ReliabilityDiagram:
    """``eval/curves/ReliabilityDiagram`` counterpart."""

    title: str
    mean_predicted_value: np.ndarray
    frac_positives: np.ndarray

    def to_dict(self) -> dict:
        return {"title": self.title,
                "mean_predicted_value": [float(v) for v in self.mean_predicted_value],
                "frac_positives": [float(v) for v in self.frac_positives]}


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50,
                 exclude_empty_bins: bool = True):
        self.rel_bins = reliability_bins
        self.hist_bins = histogram_bins
        self.exclude_empty_bins = exclude_empty_bins
        self._n_classes: Optional[int] = None

    # ------------------------------------------------------------- state
    def _init_state(self, n_classes: int) -> None:
        self._n_classes = n_classes
        b, h, c = self.rel_bins, self.hist_bins, n_classes
        # reliability: per (bin, class), matching rDiagBin* layouts
        self.rdiag_pos = np.zeros((b, c), np.int64)
        self.rdiag_total = np.zeros((b, c), np.int64)
        self.rdiag_sum_pred = np.zeros((b, c), np.float64)
        self.label_counts = np.zeros(c, np.int64)
        self.prediction_counts = np.zeros(c, np.int64)
        self.residual_overall = np.zeros(h, np.int64)
        self.residual_by_class = np.zeros((h, c), np.int64)
        self.prob_overall = np.zeros(h, np.int64)
        self.prob_by_class = np.zeros((h, c), np.int64)

    def reset(self) -> None:
        """Clear all accumulated statistics (rebuilt on the next eval)."""
        self._n_classes = None
        for f in ("rdiag_pos", "rdiag_total", "rdiag_sum_pred",
                  "label_counts", "prediction_counts", "residual_overall",
                  "residual_by_class", "prob_overall", "prob_by_class"):
            if hasattr(self, f):
                delattr(self, f)

    @property
    def num_classes(self) -> int:
        return -1 if self._n_classes is None else self._n_classes

    # --------------------------------------------------------------- eval
    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        if labels.ndim == 1:
            labels = labels[:, None]
            preds = preds[:, None]
        if labels.ndim == 3:  # [N,T,C] time series → fold time into batch
            labels = labels.reshape(-1, labels.shape[-1])
            preds = preds.reshape(-1, preds.shape[-1])
            if mask is not None:
                mask = np.asarray(mask)
                mask = (mask.reshape(-1, mask.shape[-1]) if mask.ndim == 3
                        else mask.reshape(-1))  # [N,T,C] per-output / [N,T]
        n, c = labels.shape
        if self._n_classes is None:
            self._init_state(c)
        elif c != self._n_classes:
            raise ValueError(f"n_classes changed: {self._n_classes} → {c}")

        if mask is not None:
            m = np.asarray(mask, np.float64)
            if m.ndim == 1 or (m.ndim == 2 and m.shape[1] == 1):
                m = m.reshape(-1, 1) * np.ones((1, c))  # per-example
        else:
            m = np.ones_like(labels)
        valid = m > 0
        l_masked = labels * m
        cols = np.broadcast_to(np.arange(c), labels.shape)
        pos = (l_masked > 0.5)

        # reliability bins: [j/b, (j+1)/b), last bin closed at 1.0; clip
        # keeps slightly-out-of-range values countable (old np.clip behavior)
        bins = np.clip((preds * self.rel_bins).astype(int), 0,
                       self.rel_bins - 1)
        np.add.at(self.rdiag_total, (bins[valid], cols[valid]), 1)
        pv = pos & valid
        np.add.at(self.rdiag_pos, (bins[pv], cols[pv]), 1)
        np.add.at(self.rdiag_sum_pred, (bins[valid], cols[valid]),
                  preds[valid])

        self.label_counts += pos.sum(axis=0).astype(np.int64)
        # argmax over VALID outputs only (a per-output-masked column must
        # not be countable as the predicted class)
        pred_class = np.where(valid, preds, -np.inf).argmax(axis=1)
        row_valid = valid.any(axis=1)
        np.add.at(self.prediction_counts, pred_class[row_valid], 1)

        # residual + probability histograms (positive-label rows feed the
        # per-class columns, exactly the reference's l.mul(bitmask) selection)
        resid = np.abs(labels - preds)
        rb = np.clip((resid * self.hist_bins).astype(int), 0,
                     self.hist_bins - 1)
        pb = np.clip((preds * self.hist_bins).astype(int), 0,
                     self.hist_bins - 1)
        np.add.at(self.residual_overall, rb[valid], 1)
        np.add.at(self.residual_by_class, (rb[pv], cols[pv]), 1)
        np.add.at(self.prob_overall, pb[valid], 1)
        np.add.at(self.prob_by_class, (pb[pv], cols[pv]), 1)

    def merge(self, other: "EvaluationCalibration") -> None:
        if self.rel_bins != other.rel_bins or self.hist_bins != other.hist_bins:
            raise ValueError("cannot merge calibrations with different bins")
        if other._n_classes is None:
            return
        if self._n_classes is None:
            self._init_state(other._n_classes)
        elif self._n_classes != other._n_classes:
            raise ValueError(
                f"cannot merge calibrations over different class counts "
                f"({self._n_classes} vs {other._n_classes})")
        for f in ("rdiag_pos", "rdiag_total", "rdiag_sum_pred", "label_counts",
                  "prediction_counts", "residual_overall", "residual_by_class",
                  "prob_overall", "prob_by_class"):
            setattr(self, f, getattr(self, f) + getattr(other, f))

    # ------------------------------------------------------------ getters
    def _check_class(self, class_idx: int) -> None:
        if self._n_classes is None:
            raise ValueError("no data evaluated yet (or reset() was called)")
        if not (0 <= class_idx < self._n_classes):
            raise IndexError(
                f"class index {class_idx} out of range [0, {self._n_classes})")

    def _zero_hist(self, title: str) -> Histogram:
        return Histogram(title, 0.0, 1.0,
                         np.zeros(self.hist_bins, np.int64))

    def get_reliability_diagram(self, class_idx: int) -> ReliabilityDiagram:
        """Per-class reliability curve (``getReliabilityDiagram:309``)."""
        self._check_class(class_idx)
        total = self.rdiag_total[:, class_idx].astype(np.float64)
        mean_pred = np.divide(self.rdiag_sum_pred[:, class_idx], total,
                              out=np.zeros_like(total), where=total > 0)
        frac_pos = np.divide(self.rdiag_pos[:, class_idx], total,
                             out=np.zeros_like(total), where=total > 0)
        if self.exclude_empty_bins:
            keep = total > 0
            mean_pred, frac_pos = mean_pred[keep], frac_pos[keep]
        return ReliabilityDiagram(
            f"Reliability Diagram: Class {class_idx}", mean_pred, frac_pos)

    def get_residual_plot_all_classes(self) -> Histogram:
        title = "Residual Plot - All Predictions and Classes"
        if self._n_classes is None:
            return self._zero_hist(title)
        return Histogram(title, 0.0, 1.0, self.residual_overall.copy())

    def get_residual_plot(self, class_idx: int) -> Histogram:
        self._check_class(class_idx)
        return Histogram(
            f"Residual Plot - Predictions for Label Class {class_idx}",
            0.0, 1.0, self.residual_by_class[:, class_idx].copy())

    def get_probability_histogram_all_classes(self) -> Histogram:
        title = "Network Probabilities Histogram - All Predictions and Classes"
        if self._n_classes is None:
            return self._zero_hist(title)
        return Histogram(title, 0.0, 1.0, self.prob_overall.copy())

    def get_probability_histogram(self, class_idx: int) -> Histogram:
        self._check_class(class_idx)
        return Histogram(
            f"Network Probabilities Histogram - P(class {class_idx}) - "
            f"Data Labelled Class {class_idx} Only",
            0.0, 1.0, self.prob_by_class[:, class_idx].copy())

    # ------------------------------------------- overall summary (legacy)
    def reliability_diagram(self):
        """Overall (all classes pooled): (mean predicted prob, observed
        frequency) per bin — the pre-per-class summary view. Zeros before
        any data has been evaluated (fresh or reset instance)."""
        if self._n_classes is None:
            return np.zeros(self.rel_bins), np.zeros(self.rel_bins)
        total = self.rdiag_total.sum(axis=1).astype(np.float64)
        denom = np.maximum(total, 1)
        return (self.rdiag_sum_pred.sum(axis=1) / denom,
                self.rdiag_pos.sum(axis=1) / denom)

    def expected_calibration_error(self) -> float:
        if self._n_classes is None:
            return 0.0
        mean_p, obs = self.reliability_diagram()
        counts = self.rdiag_total.sum(axis=1)
        w = counts / max(counts.sum(), 1)
        return float(np.sum(w * np.abs(mean_p - obs)))

    def stats(self) -> str:
        return (f"EvaluationCalibration(nBins={self.rel_bins}, "
                f"ECE={self.expected_calibration_error():.4f})")
