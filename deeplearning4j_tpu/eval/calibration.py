"""Calibration evaluation (DL4J ``eval/EvaluationCalibration.java``):
reliability diagram bins + residual plot histograms."""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.rel_bins = reliability_bins
        self.hist_bins = histogram_bins
        self.bin_counts = np.zeros(reliability_bins, np.int64)
        self.bin_pos = np.zeros(reliability_bins, np.int64)
        self.bin_prob_sum = np.zeros(reliability_bins, np.float64)
        self.residual_hist = np.zeros(histogram_bins, np.int64)

    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        if labels.ndim == 1:
            labels = labels[:, None]
            preds = preds[:, None]
        probs = preds.ravel()
        truth = labels.ravel()
        bins = np.clip((probs * self.rel_bins).astype(int), 0, self.rel_bins - 1)
        np.add.at(self.bin_counts, bins, 1)
        np.add.at(self.bin_pos, bins, (truth > 0.5).astype(np.int64))
        np.add.at(self.bin_prob_sum, bins, probs)
        residuals = np.abs(truth - probs)
        rbins = np.clip((residuals * self.hist_bins).astype(int), 0, self.hist_bins - 1)
        np.add.at(self.residual_hist, rbins, 1)

    def reliability_diagram(self):
        """Returns (mean_predicted_prob, observed_frequency) per bin."""
        counts = np.maximum(self.bin_counts, 1)
        return self.bin_prob_sum / counts, self.bin_pos / counts

    def expected_calibration_error(self) -> float:
        mean_p, obs = self.reliability_diagram()
        w = self.bin_counts / max(self.bin_counts.sum(), 1)
        return float(np.sum(w * np.abs(mean_p - obs)))
