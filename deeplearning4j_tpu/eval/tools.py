"""Render ROC / calibration evaluation results to standalone HTML.

Parity with ``deeplearning4j-core/.../evaluation/EvaluationTools.java``:
``roc_chart_to_html`` (ROC + precision/recall charts with an AUC header;
the ROCMultiClass/ROCBinary overloads emit one section per class) and
``export_roc_charts_to_html_file``. Charts are the dependency-free SVG
components from ``ui/components.py`` (the reference renders through its
ui-components module the same way).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass
from deeplearning4j_tpu.ui.components import (
    ChartLine,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    StyleChart,
)

__all__ = [
    "roc_chart_to_html",
    "export_roc_charts_to_html_file",
    "calibration_chart_to_html",
]

_CHART_STYLE = StyleChart(width=600, height=400)


def _single_roc_section(roc: ROC, title_suffix: str = "") -> ComponentDiv:
    _, fpr, tpr = roc.get_roc_curve()
    thr, prec, rec = roc.get_precision_recall_curve()
    auc = roc.calculate_auc()
    auc_pr = roc.calculate_auc_pr()

    header = ComponentTable(
        ["Metric", "Value"],
        [["AUC (ROC)", f"{auc:.5f}"], ["AUC (PR)", f"{auc_pr:.5f}"]])

    roc_chart = ChartLine(f"ROC: TPR/Recall (y) vs. FPR (x){title_suffix}",
                          style=_CHART_STYLE)
    roc_chart.add_series("ROC", [float(v) for v in fpr],
                         [float(v) for v in tpr])

    pr_chart = ChartLine(f"Precision (y) vs. Recall (x){title_suffix}",
                         style=_CHART_STYLE)
    pr_chart.add_series("PR", [float(v) for v in rec],
                        [float(v) for v in prec])

    pr_thr = ChartLine(
        f"Precision and Recall (y) vs. Classifier Threshold (x){title_suffix}",
        style=_CHART_STYLE)
    pr_thr.add_series("Precision", [float(v) for v in thr],
                      [float(v) for v in prec])
    pr_thr.add_series("Recall", [float(v) for v in thr],
                      [float(v) for v in rec])

    return ComponentDiv(header, roc_chart, pr_chart, pr_thr)


def _num_classes(roc) -> int:
    if isinstance(roc, ROCMultiClass):
        return roc.num_classes()
    return roc.num_labels()


def roc_chart_to_html(roc, class_names: Optional[Sequence[str]] = None) -> str:
    """Standalone HTML for a ROC / ROCMultiClass / ROCBinary result
    (``EvaluationTools.rocChartToHtml``)."""
    if isinstance(roc, ROC):
        return _single_roc_section(roc).render_page(title="ROC evaluation")

    if not isinstance(roc, (ROCBinary, ROCMultiClass)):
        raise TypeError(f"Expected ROC/ROCBinary/ROCMultiClass, got {type(roc)}")

    page = ComponentDiv()
    for c in range(_num_classes(roc)):
        name = (class_names[c] if class_names and c < len(class_names)
                else str(c))
        page.add(ComponentText(f"Class: {name}"))
        page.add(_single_roc_section(roc._single(c), f" — class {name}"))
    return page.render_page(title="ROC evaluation (multi-class)")


def export_roc_charts_to_html_file(roc, path: str,
                                   class_names: Optional[Sequence[str]] = None
                                   ) -> None:
    """Write :func:`roc_chart_to_html` output to ``path``
    (``EvaluationTools.exportRocChartsToHtmlFile``)."""
    html = roc_chart_to_html(roc, class_names=class_names)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(html)


def calibration_chart_to_html(calibration, class_idx: int = 0) -> str:
    """Reliability diagram + probability histogram page for an
    EvaluationCalibration result (EvaluationTools' calibration role)."""
    diagram = calibration.get_reliability_diagram(class_idx)
    rel = ChartLine(diagram.title, style=_CHART_STYLE)
    rel.add_series("Model",
                   [float(v) for v in diagram.mean_predicted_value],
                   [float(v) for v in diagram.frac_positives])
    rel.add_series("Perfect", [0.0, 1.0], [0.0, 1.0])

    histogram = calibration.get_probability_histogram(class_idx)
    counts = np.asarray(histogram.counts, dtype=float)
    edges = histogram.bin_edges
    centers = (edges[:-1] + edges[1:]) / 2.0
    hist = ChartLine(histogram.title, style=_CHART_STYLE)
    hist.add_series("Count", [float(v) for v in centers],
                    [float(v) for v in counts])

    return ComponentDiv(rel, hist).render_page(title="Calibration")
