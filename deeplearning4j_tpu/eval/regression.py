"""Regression evaluation (DL4J ``eval/RegressionEvaluation.java``):
per-column MSE / MAE / RMSE / RSE / PC (Pearson) / R²."""

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.labels_sum = None
        self.labels_sq_sum = None
        self.preds_sum = None
        self.preds_sq_sum = None
        self.cross_sum = None
        self.abs_err_sum = None
        self.sq_err_sum = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        if self.labels_sum is None:
            c = labels.shape[-1]
            self.labels_sum = np.zeros(c)
            self.labels_sq_sum = np.zeros(c)
            self.preds_sum = np.zeros(c)
            self.preds_sq_sum = np.zeros(c)
            self.cross_sum = np.zeros(c)
            self.abs_err_sum = np.zeros(c)
            self.sq_err_sum = np.zeros(c)
        self.n += labels.shape[0]
        self.labels_sum += labels.sum(0)
        self.labels_sq_sum += (labels ** 2).sum(0)
        self.preds_sum += predictions.sum(0)
        self.preds_sq_sum += (predictions ** 2).sum(0)
        self.cross_sum += (labels * predictions).sum(0)
        self.abs_err_sum += np.abs(labels - predictions).sum(0)
        self.sq_err_sum += ((labels - predictions) ** 2).sum(0)

    def merge(self, other: "RegressionEvaluation") -> "RegressionEvaluation":
        """Distributed merge (``BaseEvaluation.merge``): every metric here
        is derived from per-column sums, so merging is sum addition."""
        if other.labels_sum is None:
            return self
        if self.labels_sum is None:
            for a in ("labels_sum", "labels_sq_sum", "preds_sum",
                      "preds_sq_sum", "cross_sum", "abs_err_sum",
                      "sq_err_sum"):
                setattr(self, a, getattr(other, a).copy())
            self.n = other.n
            return self
        for a in ("labels_sum", "labels_sq_sum", "preds_sum",
                  "preds_sq_sum", "cross_sum", "abs_err_sum", "sq_err_sum"):
            setattr(self, a, getattr(self, a) + getattr(other, a))
        self.n += other.n
        return self

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sq_err_sum[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.abs_err_sum[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.sq_err_sum[col] / self.n))

    def relative_squared_error(self, col: int = 0) -> float:
        mean_label = self.labels_sum[col] / self.n
        denom = self.labels_sq_sum[col] - self.n * mean_label ** 2
        return float(self.sq_err_sum[col] / denom) if denom else float("inf")

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        num = self.cross_sum[col] - self.labels_sum[col] * self.preds_sum[col] / n
        d1 = self.labels_sq_sum[col] - self.labels_sum[col] ** 2 / n
        d2 = self.preds_sq_sum[col] - self.preds_sum[col] ** 2 / n
        denom = np.sqrt(d1 * d2)
        return float(num / denom) if denom else 0.0

    def r_squared(self, col: int = 0) -> float:
        mean_label = self.labels_sum[col] / self.n
        ss_tot = self.labels_sq_sum[col] - self.n * mean_label ** 2
        return float(1.0 - self.sq_err_sum[col] / ss_tot) if ss_tot else 0.0

    # -- column-averaged metrics + introspection (RegressionEvaluation.java
    #    averageX()/numColumns/reset/scoreForMetric surface) ----------------
    def num_columns(self) -> int:
        return 0 if self.labels_sum is None else len(self.labels_sum)

    def reset(self) -> None:
        self.n = 0
        for a in ("labels_sum", "labels_sq_sum", "preds_sum", "preds_sq_sum",
                  "cross_sum", "abs_err_sum", "sq_err_sum"):
            setattr(self, a, None)

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sq_err_sum / self.n))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean(self.abs_err_sum / self.n))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean([self.root_mean_squared_error(c)
                              for c in range(self.num_columns())]))

    def average_relative_squared_error(self) -> float:
        return float(np.mean([self.relative_squared_error(c)
                              for c in range(self.num_columns())]))

    def average_pearson_correlation(self) -> float:
        return float(np.mean([self.pearson_correlation(c)
                              for c in range(self.num_columns())]))

    def average_r_squared(self) -> float:
        return float(np.mean([self.r_squared(c)
                              for c in range(self.num_columns())]))

    def score_for_metric(self, metric: str) -> float:
        """Column-averaged metric by name (``scoreForMetric``): MSE, MAE,
        RMSE, RSE, PC, R2 (case-insensitive)."""
        key = metric.upper()
        table = {
            "MSE": self.average_mean_squared_error,
            "MAE": self.average_mean_absolute_error,
            "RMSE": self.average_root_mean_squared_error,
            "RSE": self.average_relative_squared_error,
            "PC": self.average_pearson_correlation,
            "R2": self.average_r_squared,
        }
        if key not in table:
            raise ValueError(f"unknown regression metric {metric!r}; "
                             f"expected one of {sorted(table)}")
        return table[key]()

    def stats(self) -> str:
        cols = len(self.labels_sum)
        lines = ["Column    MSE            MAE            RMSE           R^2"]
        for c in range(cols):
            lines.append(f"col_{c}    {self.mean_squared_error(c):.6f}    "
                         f"{self.mean_absolute_error(c):.6f}    "
                         f"{self.root_mean_squared_error(c):.6f}    "
                         f"{self.r_squared(c):.6f}")
        return "\n".join(lines)
