"""Per-output binary evaluation (DL4J ``eval/EvaluationBinary.java``):
independent accuracy/precision/recall/F1 per output column at threshold 0.5."""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationBinary:
    def __init__(self, decision_threshold: float = 0.5):
        self.threshold = decision_threshold
        self.tp = None
        self.fp = None
        self.tn = None
        self.fn = None

    def eval(self, labels, predictions, mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels) > 0.5
        preds = np.asarray(predictions) > self.threshold
        if labels.ndim == 1:
            labels = labels[:, None]
            preds = preds[:, None]
        if mask is not None:
            m = np.asarray(mask).astype(bool)
            if m.ndim == 1:
                m = m[:, None]
            valid = np.broadcast_to(m, labels.shape)
        else:
            valid = np.ones_like(labels, bool)
        c = labels.shape[-1]
        if self.tp is None:
            self.tp = np.zeros(c, np.int64)
            self.fp = np.zeros(c, np.int64)
            self.tn = np.zeros(c, np.int64)
            self.fn = np.zeros(c, np.int64)
        self.tp += np.sum(valid & labels & preds, axis=0)
        self.fp += np.sum(valid & ~labels & preds, axis=0)
        self.tn += np.sum(valid & ~labels & ~preds, axis=0)
        self.fn += np.sum(valid & labels & ~preds, axis=0)

    def merge(self, other: "EvaluationBinary") -> "EvaluationBinary":
        """Distributed merge (``BaseEvaluation.merge``): count addition."""
        if other.tp is None:
            return self
        if self.tp is None:
            self.tp, self.fp = other.tp.copy(), other.fp.copy()
            self.tn, self.fn = other.tn.copy(), other.fn.copy()
            return self
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn
        return self

    def accuracy(self, col: int = 0) -> float:
        total = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float(self.tp[col] + self.tn[col]) / max(total, 1)

    def precision(self, col: int = 0) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col]) / d if d else 0.0

    def recall(self, col: int = 0) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col]) / d if d else 0.0

    def f1(self, col: int = 0) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self, labels=None) -> str:
        """Per-output table (``EvaluationBinary.stats()``)."""
        if self.tp is None:
            raise ValueError("No evaluation data; call eval() first")
        n = len(self.tp)
        labels = labels or [f"label_{i}" for i in range(n)]
        width = max(len(str(l)) for l in labels)
        lines = ["================== Evaluation (binary) ==================",
                 f" {'':<{width}}  {'acc':>7} {'prec':>7} {'rec':>7} "
                 f"{'f1':>7} {'tp':>6} {'fp':>6} {'tn':>6} {'fn':>6}"]
        for i in range(n):
            lines.append(
                f" {labels[i]:<{width}}  {self.accuracy(i):7.4f} "
                f"{self.precision(i):7.4f} {self.recall(i):7.4f} "
                f"{self.f1(i):7.4f} {int(self.tp[i]):6d} "
                f"{int(self.fp[i]):6d} {int(self.tn[i]):6d} "
                f"{int(self.fn[i]):6d}")
        return "\n".join(lines)
