"""Classification evaluation: accuracy / precision / recall / F1 / confusion.

Reference: ``deeplearning4j-nn/.../eval/Evaluation.java:72``. Metrics follow
DL4J conventions: macro-averaged precision/recall/F1 over classes that have
at least one true/predicted instance; per-timestep rnn output is flattened
with the label mask applied.
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None, labels_list=None):
        self.num_classes = num_classes
        self.labels_list = labels_list
        self.confusion: Optional[np.ndarray] = None  # [true, predicted]

    # ----------------------------------------------------------------- eval
    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # [N,T,C] → flatten time, applying mask
            n, t, c = labels.shape
            labels = labels.reshape(n * t, c)
            predictions = predictions.reshape(n * t, -1)
            if mask is not None:
                m = np.asarray(mask).reshape(n * t).astype(bool)
                labels = labels[m]
                predictions = predictions[m]
        elif mask is not None:
            m = np.asarray(mask).astype(bool).ravel()
            labels = labels[m]
            predictions = predictions[m]

        if labels.ndim == 2 and labels.shape[1] > 1:
            true_idx = np.argmax(labels, axis=1)
            nc = labels.shape[1]
        else:
            true_idx = labels.astype(int).ravel()
            nc = self.num_classes or int(max(true_idx.max(), 0)) + 1
        if predictions.ndim == 2 and predictions.shape[1] > 1:
            pred_idx = np.argmax(predictions, axis=1)
            nc = max(nc, predictions.shape[1])
        else:
            pred_idx = (predictions.ravel() > 0.5).astype(int)
            nc = max(nc, 2)

        # grow the confusion matrix if a later batch reveals a higher class
        needed = max(nc, int(true_idx.max(initial=0)) + 1,
                     int(pred_idx.max(initial=0)) + 1,
                     self.num_classes or 0)
        if self.num_classes is None or needed > self.num_classes:
            old = self.confusion
            self.num_classes = needed
            self.confusion = np.zeros((needed, needed), np.int64)
            if old is not None:
                self.confusion[:old.shape[0], :old.shape[1]] = old
        elif self.confusion is None:
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)

    def eval_time_series(self, labels, predictions, labels_mask=None):
        self.eval(labels, predictions, mask=labels_mask)

    # -------------------------------------------------------------- metrics
    def _check(self):
        if self.confusion is None:
            raise ValueError("No evaluation data; call eval() first")

    def accuracy(self) -> float:
        self._check()
        total = self.confusion.sum()
        return float(np.trace(self.confusion)) / max(total, 1)

    def _tp(self, i) -> int:
        return int(self.confusion[i, i])

    def _fp(self, i) -> int:
        return int(self.confusion[:, i].sum() - self.confusion[i, i])

    def _fn(self, i) -> int:
        return int(self.confusion[i, :].sum() - self.confusion[i, i])

    def precision(self, cls: Optional[int] = None) -> float:
        self._check()
        if cls is not None:
            denom = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if self.confusion[:, i].sum() + self.confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        self._check()
        if cls is not None:
            denom = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if self.confusion[:, i].sum() + self.confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        self._check()
        vals = [self.f1(i) for i in range(self.num_classes)
                if self.confusion[:, i].sum() + self.confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def false_positive_rate(self, cls: int) -> float:
        self._check()
        tn = self.confusion.sum() - self._tp(cls) - self._fp(cls) - self._fn(cls)
        denom = self._fp(cls) + tn
        return self._fp(cls) / denom if denom else 0.0

    def matthews_correlation(self, cls: int) -> float:
        self._check()
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = int(self.confusion.sum()) - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return ((tp * tn - fp * fn) / denom) if denom else 0.0

    def confusion_matrix(self) -> np.ndarray:
        self._check()
        return self.confusion.copy()

    def merge(self, other: "Evaluation") -> "Evaluation":
        if other.confusion is not None:
            if self.confusion is None:
                self.num_classes = other.num_classes
                self.confusion = other.confusion.copy()
            else:
                self.confusion += other.confusion
        return self

    # ---------------------------------------------------------------- serde
    def to_json(self) -> str:
        return json.dumps({
            "num_classes": self.num_classes,
            "confusion": None if self.confusion is None else self.confusion.tolist(),
        })

    @staticmethod
    def from_json(s: str) -> "Evaluation":
        d = json.loads(s)
        e = Evaluation(num_classes=d["num_classes"])
        if d["confusion"] is not None:
            e.confusion = np.asarray(d["confusion"], np.int64)
        return e

    def stats(self) -> str:
        self._check()
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
            str(self.confusion),
        ]
        return "\n".join(lines)
