"""Classification evaluation: accuracy / precision / recall / F1 / confusion.

Reference: ``deeplearning4j-nn/.../eval/Evaluation.java:72``. Metrics follow
DL4J conventions: macro-averaged precision/recall/F1 over classes that have
at least one true/predicted instance; per-timestep rnn output is flattened
with the label mask applied.

Depth features beyond the basics:
- **top-N accuracy** (``Evaluation.java:144`` constructor, counting at
  ``:437-455``): an example is top-N correct when fewer than N other class
  probabilities are strictly greater than the true class's probability.
- **prediction recording with metadata** (``Evaluation.java:1481``
  ``addToMetaConfusionMatrix``, ``:1506`` ``getPredictionErrors``): pass
  ``record_meta_data`` (e.g. from a ``RecordReaderDataSetIterator`` with
  ``collect_meta_data=True``) to ``eval`` and drill into per-record errors
  afterwards.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Prediction:
    """One recorded prediction (``eval/meta/Prediction.java``)."""

    actual: int
    predicted: int
    record_meta_data: Any

    def get_record_meta_data(self):
        return self.record_meta_data


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None, labels_list=None,
                 top_n: int = 1):
        self.num_classes = num_classes
        self._initial_num_classes = num_classes  # restored by reset()
        self.labels_list = labels_list
        self.confusion: Optional[np.ndarray] = None  # [true, predicted]
        self.top_n = max(int(top_n), 1)
        self.top_n_correct_count = 0
        self.top_n_total_count = 0
        # (actual, predicted) → list of metadata; None until metadata seen
        self.confusion_meta: Optional[
            Dict[Tuple[int, int], List[Any]]] = None

    # ----------------------------------------------------------------- eval
    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None,
             record_meta_data: Optional[List[Any]] = None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # [N,T,C] → flatten time, applying mask
            n, t, c = labels.shape
            labels = labels.reshape(n * t, c)
            predictions = predictions.reshape(n * t, -1)
            if record_meta_data is not None:
                record_meta_data = [m for m in record_meta_data
                                    for _ in range(t)]
            if mask is not None:
                m = np.asarray(mask).reshape(n * t).astype(bool)
                labels = labels[m]
                predictions = predictions[m]
                if record_meta_data is not None:
                    record_meta_data = [x for x, keep
                                        in zip(record_meta_data, m) if keep]
        elif mask is not None:
            m = np.asarray(mask).astype(bool).ravel()
            labels = labels[m]
            predictions = predictions[m]
            if record_meta_data is not None:
                record_meta_data = [x for x, keep
                                    in zip(record_meta_data, m) if keep]

        if labels.ndim == 2 and labels.shape[1] > 1:
            true_idx = np.argmax(labels, axis=1)
            nc = labels.shape[1]
        else:
            true_idx = labels.astype(int).ravel()
            nc = self.num_classes or int(max(true_idx.max(), 0)) + 1
        if predictions.ndim == 2 and predictions.shape[1] > 1:
            pred_idx = np.argmax(predictions, axis=1)
            nc = max(nc, predictions.shape[1])
        else:
            pred_idx = (predictions.ravel() > 0.5).astype(int)
            nc = max(nc, 2)

        # grow the confusion matrix if a later batch reveals a higher class
        needed = max(nc, int(true_idx.max(initial=0)) + 1,
                     int(pred_idx.max(initial=0)) + 1,
                     self.num_classes or 0)
        if self.num_classes is None or needed > self.num_classes:
            old = self.confusion
            self.num_classes = needed
            self.confusion = np.zeros((needed, needed), np.int64)
            if old is not None:
                self.confusion[:old.shape[0], :old.shape[1]] = old
        elif self.confusion is None:
            self.confusion = np.zeros((self.num_classes, self.num_classes), np.int64)
        np.add.at(self.confusion, (true_idx, pred_idx), 1)

        # top-N accuracy (Evaluation.java:437: top-N correct when fewer
        # than N probabilities are STRICTLY greater than the true class's)
        if (self.top_n > 1 and predictions.ndim == 2
                and predictions.shape[1] > 1):
            true_prob = predictions[np.arange(len(true_idx)), true_idx]
            greater = (predictions > true_prob[:, None]).sum(axis=1)
            self.top_n_correct_count += int((greater < self.top_n).sum())
            self.top_n_total_count += len(true_idx)

        # per-record metadata → meta confusion matrix
        # (Evaluation.java:1481 addToMetaConfusionMatrix)
        if record_meta_data is not None:
            if len(record_meta_data) != len(true_idx):
                raise ValueError(
                    f"record_meta_data length {len(record_meta_data)} != "
                    f"number of (unmasked) examples {len(true_idx)}")
            if self.confusion_meta is None:
                self.confusion_meta = {}
            for a, p, m in zip(true_idx, pred_idx, record_meta_data):
                self.confusion_meta.setdefault((int(a), int(p)), []).append(m)

    def eval_time_series(self, labels, predictions, labels_mask=None):
        self.eval(labels, predictions, mask=labels_mask)

    # -------------------------------------------------------------- metrics
    def _check(self):
        if self.confusion is None:
            raise ValueError("No evaluation data; call eval() first")

    def accuracy(self) -> float:
        self._check()
        total = self.confusion.sum()
        return float(np.trace(self.confusion)) / max(total, 1)

    def top_n_accuracy(self) -> float:
        """``Evaluation.java:1159``: fraction of examples whose true class
        probability is among the N highest. Equals ``accuracy()`` when
        ``top_n == 1``."""
        if self.top_n <= 1:
            return self.accuracy()
        if self.top_n_total_count == 0:
            return 0.0
        return self.top_n_correct_count / self.top_n_total_count

    def _tp(self, i) -> int:
        return int(self.confusion[i, i])

    def _fp(self, i) -> int:
        return int(self.confusion[:, i].sum() - self.confusion[i, i])

    def _fn(self, i) -> int:
        return int(self.confusion[i, :].sum() - self.confusion[i, i])

    def precision(self, cls: Optional[int] = None) -> float:
        self._check()
        if cls is not None:
            denom = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if self.confusion[:, i].sum() + self.confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        self._check()
        if cls is not None:
            denom = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if self.confusion[:, i].sum() + self.confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f_beta(self, beta: float, cls: Optional[int] = None,
               averaging: str = "macro") -> float:
        """``Evaluation.fBeta(beta, class)`` — F-measure with recall
        weighted beta times as much as precision."""
        self._check()
        b2 = beta * beta
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            denom = b2 * p + r
            return (1 + b2) * p * r / denom if denom else 0.0
        if averaging == "macro":
            cs = self._support_classes()
            return float(np.mean([self.f_beta(beta, i) for i in cs])) \
                if cs else 0.0
        p = self.precision_averaged("micro")
        r = self.recall_averaged("micro")
        denom = b2 * p + r
        return (1 + b2) * p * r / denom if denom else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        self._check()
        vals = [self.f1(i) for i in range(self.num_classes)
                if self.confusion[:, i].sum() + self.confusion[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    # ------------------------------------------- averaging / extra metrics
    def _counts(self, i):
        tp = self._tp(i)
        fp = self._fp(i)
        fn = self._fn(i)
        tn = int(self.confusion.sum()) - tp - fp - fn
        return tp, fp, fn, tn

    # -- per-class count maps (Evaluation.java truePositives() family) ------
    def true_positives(self) -> Dict[int, int]:
        self._check()
        return {i: self._tp(i) for i in range(self.num_classes)}

    def false_positives(self) -> Dict[int, int]:
        self._check()
        return {i: self._fp(i) for i in range(self.num_classes)}

    def false_negatives(self) -> Dict[int, int]:
        self._check()
        return {i: self._fn(i) for i in range(self.num_classes)}

    def true_negatives(self) -> Dict[int, int]:
        self._check()
        return {i: self._counts(i)[3] for i in range(self.num_classes)}

    def positive(self) -> Dict[int, int]:
        """Actual count per class (``positive()``)."""
        self._check()
        return {i: int(self.confusion[i, :].sum())
                for i in range(self.num_classes)}

    def negative(self) -> Dict[int, int]:
        """Actual-negative count per class (``negative()``)."""
        self._check()
        total = int(self.confusion.sum())
        return {i: total - p for i, p in self.positive().items()}

    def false_negative_rate(self, cls: int, edge_case: float = 0.0) -> float:
        """FN / (FN + TP) (``falseNegativeRate``)."""
        self._check()
        tp, _, fn, _ = self._counts(cls)
        return fn / (fn + tp) if (fn + tp) else edge_case

    def false_alarm_rate(self) -> float:
        """Mean of macro FPR and FNR (``falseAlarmRate``)."""
        self._check()
        fpr = np.mean([self.false_positive_rate(i)
                       for i in range(self.num_classes)])
        fnr = np.mean([self.false_negative_rate(i)
                       for i in range(self.num_classes)])
        return float((fpr + fnr) / 2.0)

    def class_count(self, cls: int) -> int:
        """Actual instances of a class (``classCount``)."""
        self._check()
        return int(self.confusion[cls, :].sum())

    def get_num_row_counter(self) -> int:
        """Total examples seen (``getNumRowCounter``)."""
        return 0 if self.confusion is None else int(self.confusion.sum())

    def get_class_label(self, cls: int) -> str:
        """Label string for a class index (``getClassLabel``)."""
        if self.labels_list and cls < len(self.labels_list):
            return str(self.labels_list[cls])
        return str(cls)

    def get_top_n_correct_count(self) -> int:
        return self.top_n_correct_count

    def get_top_n_total_count(self) -> int:
        return self.top_n_total_count

    def reset(self) -> None:
        """Clear all accumulated state (``reset()``), restoring the
        constructor's class count."""
        self.confusion = None
        if self._initial_num_classes is not None:
            self.num_classes = self._initial_num_classes
        elif self.labels_list is not None:
            self.num_classes = len(self.labels_list)
        else:
            self.num_classes = None
        self.top_n_correct_count = 0
        self.top_n_total_count = 0
        self.confusion_meta = None

    def confusion_to_string(self) -> str:
        """Formatted confusion matrix (``confusionToString``): predicted
        classes across, actual down."""
        self._check()
        names = [self.get_class_label(i) for i in range(self.num_classes)]
        width = max(6, max(len(n) for n in names) + 1)
        head = " " * width + "".join(f"{n:>{width}}" for n in names)
        rows = [head]
        for i in range(self.num_classes):
            cells = "".join(f"{int(self.confusion[i, j]):>{width}}"
                            for j in range(self.num_classes))
            rows.append(f"{names[i]:>{width}}" + cells)
        rows.append("")
        rows.append(f"Confusion matrix format: Actual (rowClass) predicted "
                    f"as (columnClass) N times")
        return "\n".join(rows)

    def _support_classes(self):
        """Classes with at least one true or predicted instance — the
        subset this framework's macro averages run over (consistent with
        ``precision()``/``recall()``/``f1()``)."""
        return [i for i in range(self.num_classes)
                if self.confusion[:, i].sum()
                + self.confusion[i, :].sum() > 0]

    def _num_classes_excluded(self) -> int:
        """Classes left out of the macro averages for lack of support
        (``averageF1NumClassesExcluded`` family)."""
        self._check()
        return self.num_classes - len(self._support_classes())

    def average_f1_num_classes_excluded(self) -> int:
        return self._num_classes_excluded()

    def average_f_beta_num_classes_excluded(self) -> int:
        return self._num_classes_excluded()

    def average_precision_num_classes_excluded(self) -> int:
        return self._num_classes_excluded()

    def average_recall_num_classes_excluded(self) -> int:
        return self._num_classes_excluded()

    def precision_averaged(self, averaging: str = "macro") -> float:
        """``Evaluation.precision(EvaluationAveraging)``: macro averages
        per-class values (over supported classes, matching ``precision()``
        — the reference divides by ALL classes); micro pools counts."""
        self._check()
        if averaging == "macro":
            cs = self._support_classes()
            return float(np.mean([self.precision(i) for i in cs])) if cs \
                else 0.0
        tp = sum(self._tp(i) for i in range(self.num_classes))
        fp = sum(self._fp(i) for i in range(self.num_classes))
        return tp / (tp + fp) if tp + fp else 0.0

    def recall_averaged(self, averaging: str = "macro") -> float:
        self._check()
        if averaging == "macro":
            cs = self._support_classes()
            return float(np.mean([self.recall(i) for i in cs])) if cs \
                else 0.0
        tp = sum(self._tp(i) for i in range(self.num_classes))
        fn = sum(self._fn(i) for i in range(self.num_classes))
        return tp / (tp + fn) if tp + fn else 0.0

    def g_measure(self, cls: Optional[int] = None,
                  averaging: str = "macro") -> float:
        """Geometric mean of precision and recall
        (``Evaluation.gMeasure``)."""
        self._check()
        if cls is not None:
            return float(np.sqrt(self.precision(cls) * self.recall(cls)))
        if averaging == "macro":
            cs = self._support_classes()
            return float(np.mean([self.g_measure(i) for i in cs])) if cs \
                else 0.0
        p = self.precision_averaged("micro")
        r = self.recall_averaged("micro")
        return float(np.sqrt(p * r))

    def matthews_correlation_averaged(self, averaging: str = "macro"
                                      ) -> float:
        """``Evaluation.matthewsCorrelation(EvaluationAveraging)``."""
        self._check()
        if averaging == "macro":
            cs = self._support_classes()
            return float(np.mean([self.matthews_correlation(i)
                                  for i in cs])) if cs else 0.0
        tp, fp, fn, tn = (sum(self._counts(i)[j]
                              for i in range(self.num_classes))
                          for j in range(4))
        denom = np.sqrt(float((tp + fp) * (tp + fn)
                              * (tn + fp) * (tn + fn)))
        return ((tp * tn - fp * fn) / denom) if denom else 0.0

    def score_for_metric(self, metric: str) -> float:
        """``Evaluation.scoreForMetric(Metric)`` — the hook early-stopping
        score calculators select on: ACCURACY, F1, PRECISION, RECALL,
        GMEASURE, MCC (case-insensitive)."""
        m = metric.upper()
        if m == "ACCURACY":
            return self.accuracy()
        if m == "F1":
            return self.f1()
        if m == "PRECISION":
            return self.precision()
        if m == "RECALL":
            return self.recall()
        if m == "GMEASURE":
            return self.g_measure(averaging="macro")
        if m == "MCC":
            return self.matthews_correlation_averaged("macro")
        raise ValueError(f"Unknown metric: {metric}")

    def false_positive_rate(self, cls: int) -> float:
        self._check()
        tn = self.confusion.sum() - self._tp(cls) - self._fp(cls) - self._fn(cls)
        denom = self._fp(cls) + tn
        return self._fp(cls) / denom if denom else 0.0

    def matthews_correlation(self, cls: int) -> float:
        self._check()
        tp, fp, fn = self._tp(cls), self._fp(cls), self._fn(cls)
        tn = int(self.confusion.sum()) - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return ((tp * tn - fp * fn) / denom) if denom else 0.0

    def confusion_matrix(self) -> np.ndarray:
        self._check()
        return self.confusion.copy()

    # -------------------------------------------- prediction introspection
    def get_prediction_errors(self) -> Optional[List[Prediction]]:
        """Per-record misclassifications (``Evaluation.java:1506``), sorted
        by (actual, predicted). Only available when ``eval`` was called with
        ``record_meta_data``; returns None otherwise (reference contract)."""
        if self.confusion_meta is None:
            return None
        out: List[Prediction] = []
        for (a, p) in sorted(self.confusion_meta):
            if a == p:
                continue
            out.extend(Prediction(a, p, m) for m in self.confusion_meta[(a, p)])
        return out

    def get_predictions_by_actual_class(self, actual_class: int
                                        ) -> Optional[List[Prediction]]:
        """All recorded predictions whose TRUE class is ``actual_class``
        (``Evaluation.java:1554``)."""
        if self.confusion_meta is None:
            return None
        return [Prediction(a, p, m)
                for (a, p), ms in self.confusion_meta.items()
                if a == actual_class for m in ms]

    def get_prediction_by_predicted_class(self, predicted_class: int
                                          ) -> Optional[List[Prediction]]:
        """All recorded predictions whose PREDICTED class is
        ``predicted_class`` (``Evaluation.java:1583``)."""
        if self.confusion_meta is None:
            return None
        return [Prediction(a, p, m)
                for (a, p), ms in self.confusion_meta.items()
                if p == predicted_class for m in ms]

    def get_predictions(self, actual_class: int, predicted_class: int
                        ) -> Optional[List[Prediction]]:
        """Recorded predictions for one confusion-matrix cell
        (``Evaluation.java:1610``)."""
        if self.confusion_meta is None:
            return None
        return [Prediction(actual_class, predicted_class, m)
                for m in self.confusion_meta.get(
                    (actual_class, predicted_class), [])]

    def merge(self, other: "Evaluation") -> "Evaluation":
        if other.confusion is not None:
            if self.confusion is None:
                self.num_classes = other.num_classes
                self.confusion = other.confusion.copy()
            else:
                if other.confusion.shape[0] > self.confusion.shape[0]:
                    grown = np.zeros_like(other.confusion)
                    grown[:self.confusion.shape[0],
                          :self.confusion.shape[1]] = self.confusion
                    self.confusion = grown
                    self.num_classes = other.num_classes
                self.confusion[:other.confusion.shape[0],
                               :other.confusion.shape[1]] += other.confusion
        self.top_n_correct_count += other.top_n_correct_count
        self.top_n_total_count += other.top_n_total_count
        if other.confusion_meta is not None:
            if self.confusion_meta is None:
                self.confusion_meta = {}
            for k, ms in other.confusion_meta.items():
                self.confusion_meta.setdefault(k, []).extend(ms)
        return self

    # ---------------------------------------------------------------- serde
    def to_json(self) -> str:
        return json.dumps({
            "num_classes": self.num_classes,
            "confusion": None if self.confusion is None else self.confusion.tolist(),
            "top_n": self.top_n,
            "top_n_correct_count": self.top_n_correct_count,
            "top_n_total_count": self.top_n_total_count,
            "labels_list": self.labels_list,
        })

    @staticmethod
    def from_json(s: str) -> "Evaluation":
        d = json.loads(s)
        e = Evaluation(num_classes=d["num_classes"], top_n=d.get("top_n", 1),
                       labels_list=d.get("labels_list"))
        if d["confusion"] is not None:
            e.confusion = np.asarray(d["confusion"], np.int64)
        e.top_n_correct_count = d.get("top_n_correct_count", 0)
        e.top_n_total_count = d.get("top_n_total_count", 0)
        return e

    def stats(self) -> str:
        self._check()
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
        ]
        if self.top_n > 1 and self.top_n_total_count > 0:
            lines.append(
                f" Top {self.top_n} Accuracy:  {self.top_n_accuracy():.4f}")
        lines += [
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
        ]
        if self.labels_list:
            # labeled per-class block (Evaluation.stats() label output)
            width = max(len(str(l)) for l in self.labels_list)
            for i in range(self.num_classes):
                name = (self.labels_list[i] if i < len(self.labels_list)
                        else str(i))
                lines.append(
                    f" {name:<{width}}  " + " ".join(
                        f"{int(v):6d}" for v in self.confusion[i]))
        else:
            lines.append(str(self.confusion))
        return "\n".join(lines)
