"""Cloud provisioning & storage: the reference's AWS module, TPU-native.

The reference ships `deeplearning4j-aws` (EC2 provisioning
`aws/ec2/provision/HostProvisioner.java`, S3 up/download, EMR). The
TPU-native equivalents are GCP: TPU-VM provisioning through ``gcloud`` and
object storage through GCS — with S3 kept for capability parity. Everything
is gated: command builders always work (and are unit-testable); execution
requires the respective CLI/SDK which this image does not bundle, and a
``file://`` scheme provides a local emulation path for tests.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional
from urllib.parse import urlparse


class TpuProvisioner:
    """Builds (and optionally runs) ``gcloud compute tpus tpu-vm`` commands —
    the HostProvisioner role for TPU slices."""

    def __init__(self, project: str, zone: str, runner=None):
        self.project = project
        self.zone = zone
        self._runner = runner or self._run

    @staticmethod
    def _run(cmd: List[str]) -> str:
        if shutil.which(cmd[0]) is None:
            raise RuntimeError(
                f"{cmd[0]!r} CLI not available in this environment; use the "
                "returned command on a workstation with gcloud installed")
        return subprocess.run(cmd, check=True, capture_output=True,
                              text=True).stdout

    def create_command(self, name: str, accelerator_type: str = "v5p-8",
                       version: str = "tpu-ubuntu2204-base") -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "create", name,
                f"--project={self.project}", f"--zone={self.zone}",
                f"--accelerator-type={accelerator_type}",
                f"--version={version}"]

    def delete_command(self, name: str) -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "delete", name,
                f"--project={self.project}", f"--zone={self.zone}", "--quiet"]

    def ssh_command(self, name: str, command: str,
                    worker: str = "all") -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
                f"--project={self.project}", f"--zone={self.zone}",
                f"--worker={worker}", f"--command={command}"]

    def run_command(self, cmd: List[str]) -> str:
        """Execute an arbitrary built command through the configured runner
        (the single dispatch point — inject a fake runner to test/log)."""
        return self._runner(cmd)

    def create(self, name: str, **kw) -> str:
        return self._runner(self.create_command(name, **kw))

    def delete(self, name: str) -> str:
        return self._runner(self.delete_command(name))

    def run_on(self, name: str, command: str, **kw) -> str:
        return self._runner(self.ssh_command(name, command, **kw))


class ObjectStorage:
    """Upload/download against gs:// (google-cloud-storage), s3://  (boto3),
    or file:// (always available — the test/emulation path). The reference's
    S3Uploader/S3Downloader role."""

    def upload(self, local_path: str, uri: str) -> None:
        scheme, bucket, key = self._parse(uri)
        if scheme == "file":
            dest = os.path.join(bucket, key.lstrip("/"))
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            shutil.copyfile(local_path, dest)
        elif scheme == "gs":
            client = self._gcs()
            client.bucket(bucket).blob(key.lstrip("/")).upload_from_filename(
                local_path)
        elif scheme == "s3":
            self._s3().upload_file(local_path, bucket, key.lstrip("/"))
        else:
            raise ValueError(f"unsupported scheme {scheme!r}")

    def download(self, uri: str, local_path: str) -> None:
        scheme, bucket, key = self._parse(uri)
        if scheme == "file":
            shutil.copyfile(os.path.join(bucket, key.lstrip("/")), local_path)
        elif scheme == "gs":
            client = self._gcs()
            client.bucket(bucket).blob(key.lstrip("/")).download_to_filename(
                local_path)
        elif scheme == "s3":
            self._s3().download_file(bucket, key.lstrip("/"), local_path)
        else:
            raise ValueError(f"unsupported scheme {scheme!r}")

    @staticmethod
    def _parse(uri: str):
        p = urlparse(uri)
        if p.scheme == "file":
            # file:///tmp/bucket/key → bucket=/tmp/bucket-part? keep it simple:
            # everything up to the last component is the "bucket" directory
            full = p.path
            return "file", os.path.dirname(full), os.path.basename(full)
        return p.scheme, p.netloc, p.path

    @staticmethod
    def _gcs():
        try:
            from google.cloud import storage
        except ImportError as e:
            raise ImportError("google-cloud-storage is not installed; "
                              "use file:// URIs for local staging") from e
        return storage.Client()

    @staticmethod
    def _s3():
        try:
            import boto3
        except ImportError as e:
            raise ImportError("boto3 is not installed; "
                              "use file:// URIs for local staging") from e
        return boto3.client("s3")


class HostProvisioner:
    """Per-host script staging + execution over the TPU-VM ssh/scp channel
    (``aws/ec2/provision/HostProvisioner.java`` role: ``uploadAndRun``,
    ``runRemoteCommand``, ``uploadForDeployment`` — JSch sessions become
    ``gcloud compute tpus tpu-vm ssh/scp`` invocations)."""

    def __init__(self, provisioner: TpuProvisioner, name: str,
                 worker: str = "all"):
        self.provisioner = provisioner
        self.name = name
        self.worker = worker

    def scp_command(self, local_path: str, remote_path: str) -> List[str]:
        p = self.provisioner
        return ["gcloud", "compute", "tpus", "tpu-vm", "scp", local_path,
                f"{self.name}:{remote_path}",
                f"--project={p.project}", f"--zone={p.zone}",
                f"--worker={self.worker}"]

    def upload_for_deployment(self, local_path: str, remote_path: str) -> str:
        """``uploadForDeployment``: stage a file on every worker."""
        return self.provisioner.run_command(
            self.scp_command(local_path, remote_path))

    def run_remote_command(self, command: str) -> str:
        return self.provisioner.run_on(self.name, command, worker=self.worker)

    def upload_and_run(self, script_path: str, root_dir: str = "/tmp") -> str:
        """``uploadAndRun``: stage a setup script and execute it.

        ``~``-rooted paths are staged via scp's native tilde handling and
        executed via ``$HOME`` inside double quotes (``shlex.quote`` would
        freeze the tilde as a literal)."""
        import posixpath
        import shlex
        remote = posixpath.join(root_dir, os.path.basename(script_path))
        self.upload_for_deployment(script_path, remote)
        if remote == "~" or remote.startswith("~/"):
            # "$HOME" expands; the rest stays shlex-quoted so metacharacters
            # in the basename can never execute remotely
            q = '"$HOME"' + (("/" + shlex.quote(remote[2:])) if len(remote) > 2
                             else "")
        else:
            q = shlex.quote(remote)
        return self.run_remote_command(f"chmod +x {q} && {q}")


class ClusterProvisioner:
    """Bring up N single-host TPU VMs (or one multi-host slice), wait until
    they are READY, provision them in parallel, tear them down — the
    ``Ec2BoxCreator`` + ``ClusterSetup`` orchestration
    (``ec2/provision/ClusterSetup.java``: create boxes, blockTillAllRunning,
    provision workers on a thread pool)."""

    def __init__(self, provisioner: TpuProvisioner, num_workers: int = 1,
                 accelerator_type: str = "v5p-8",
                 version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "dl4j-tpu"):
        self.provisioner = provisioner
        self.num_workers = num_workers
        self.accelerator_type = accelerator_type
        self.version = version
        self.name_prefix = name_prefix

    @property
    def names(self) -> List[str]:
        return [f"{self.name_prefix}-{i}" for i in range(self.num_workers)]

    def describe_command(self, name: str) -> List[str]:
        p = self.provisioner
        return ["gcloud", "compute", "tpus", "tpu-vm", "describe", name,
                f"--project={p.project}", f"--zone={p.zone}",
                "--format=value(state)"]

    def _pool(self):
        from concurrent.futures import ThreadPoolExecutor
        return ThreadPoolExecutor(max_workers=max(1, min(8, self.num_workers)))

    def create(self) -> List[str]:
        """Create every VM in parallel (``Ec2BoxCreator.create``; creation is
        the slowest step — minutes per node); returns the names."""
        if not self.names:
            return []
        with self._pool() as ex:
            list(ex.map(lambda n: self.provisioner.create(
                n, accelerator_type=self.accelerator_type,
                version=self.version), self.names))
        return self.names

    def block_till_all_running(self, poll_seconds: float = 10.0,
                               timeout: float = 900.0) -> None:
        """``blockTillAllRunning``: poll describe until every VM is READY."""
        import time as _time
        deadline = _time.monotonic() + timeout
        pending = list(self.names)
        while pending:
            still = []
            for name in pending:
                state = self.provisioner.run_command(
                    self.describe_command(name)).strip().upper()
                if state != "READY":
                    still.append(name)
            if not still:
                return
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"TPU VMs not READY within {timeout}s: {still}")
            _time.sleep(poll_seconds)
            pending = still

    def provision_workers(self, setup_script: str) -> List[str]:
        """Run the worker setup script on every VM in parallel
        (``ClusterSetup.provisionWorkers`` thread pool)."""
        if not self.names:
            return []
        def one(name):
            return HostProvisioner(self.provisioner, name).upload_and_run(
                setup_script)
        with self._pool() as ex:
            return list(ex.map(one, self.names))

    def teardown(self) -> None:
        """Delete every VM; per-VM failures are collected as warnings so a
        teardown after a PARTIAL create (some VMs never existed) still
        removes the ones that do, and never masks the original error."""
        if not self.names:
            return
        import warnings

        def one(name):
            try:
                self.provisioner.delete(name)
            except Exception as e:  # noqa: BLE001 - best-effort cleanup
                warnings.warn(f"teardown: could not delete {name}: {e}",
                              stacklevel=2)

        with self._pool() as ex:
            list(ex.map(one, self.names))


class BucketDataSetIterator:
    """Iterate serialized DataSets straight out of object storage
    (``s3/reader/BaseS3DataSetIterator.java`` + ``BucketIterator`` role).

    Keys are listed from the bucket URI (works with ``file://`` locally —
    the test/emulation path, like every storage entry point here), each
    object is fetched and deserialized with ``datasets.dataset.DataSet``'s
    npz layout (features/labels [+ masks])."""

    def __init__(self, bucket_uri: str, storage: Optional[ObjectStorage] = None,
                 suffix: str = ".npz"):
        self.bucket_uri = bucket_uri.rstrip("/")
        self.storage = storage or ObjectStorage()
        self.suffix = suffix
        self._keys = self.list_keys()
        self._pos = 0

    def _prefix(self):
        """(scheme, bucket, key_prefix) of the bucket URI itself. The prefix
        keeps its trailing '/' (when non-empty) so sibling prefixes like
        ``data-old/`` never match a ``data/`` listing."""
        from urllib.parse import urlparse
        p = urlparse(self.bucket_uri)
        prefix = p.path.lstrip("/")
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        return p.scheme, p.netloc, prefix

    def list_keys(self) -> List[str]:
        """Keys RELATIVE to the bucket URI (nested keys keep their
        subpath, so ``__next__`` re-joins to a real object URI)."""
        scheme, bucket, prefix = self._prefix()
        if scheme == "file":
            root = self.bucket_uri[len("file://"):]
            if not os.path.isdir(root):
                return []
            out = []
            for base, _dirs, files in os.walk(root):
                rel = os.path.relpath(base, root)
                for n in files:
                    if n.endswith(self.suffix):
                        out.append(n if rel == "." else os.path.join(rel, n))
            return sorted(out)
        if scheme == "gs":
            client = ObjectStorage._gcs()
            return sorted(b.name[len(prefix):]
                          for b in client.bucket(bucket).list_blobs(prefix=prefix)
                          if b.name.endswith(self.suffix))
        if scheme == "s3":
            s3 = ObjectStorage._s3()
            keys: List[str] = []
            token = None
            while True:  # paginate: list_objects_v2 caps at 1000 keys
                kw = {"Bucket": bucket, "Prefix": prefix}
                if token:
                    kw["ContinuationToken"] = token
                resp = s3.list_objects_v2(**kw)
                keys.extend(o["Key"][len(prefix):]
                            for o in resp.get("Contents", ())
                            if o["Key"].endswith(self.suffix))
                if not resp.get("IsTruncated"):
                    break
                token = resp.get("NextContinuationToken")
            return sorted(keys)
        raise ValueError(f"unsupported scheme {scheme!r}")

    def reset(self) -> None:
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._keys)

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        import tempfile

        import numpy as np

        from deeplearning4j_tpu.datasets.dataset import DataSet
        if not self.has_next():
            raise StopIteration
        key = self._keys[self._pos]
        self._pos += 1
        with tempfile.TemporaryDirectory() as d:
            local = os.path.join(d, os.path.basename(key))
            self.storage.download(f"{self.bucket_uri}/{key}", local)
            with np.load(local, allow_pickle=False) as z:
                return DataSet(z["features"], z["labels"],
                               z["features_mask"] if "features_mask" in z else None,
                               z["labels_mask"] if "labels_mask" in z else None)

    @staticmethod
    def stage(datasets, bucket_uri: str,
              storage: Optional[ObjectStorage] = None,
              prefix: str = "part") -> List[str]:
        """Serialize DataSets into the bucket (the uploader half;
        ``S3Uploader`` role). Returns the written keys."""
        import tempfile

        import numpy as np
        storage = storage or ObjectStorage()
        keys = []
        for i, ds in enumerate(datasets):
            key = f"{prefix}-{i:05d}.npz"
            with tempfile.TemporaryDirectory() as d:
                local = os.path.join(d, key)
                arrs = {"features": np.asarray(ds.features),
                        "labels": np.asarray(ds.labels)}
                if ds.features_mask is not None:
                    arrs["features_mask"] = np.asarray(ds.features_mask)
                if ds.labels_mask is not None:
                    arrs["labels_mask"] = np.asarray(ds.labels_mask)
                np.savez(local, **arrs)
                storage.upload(local, f"{bucket_uri.rstrip('/')}/{key}")
            keys.append(key)
        return keys


class TpuJobRunner:
    """Ephemeral-cluster job execution: provision → stage → run → collect →
    teardown (the ``emr/SparkEMRClient.java`` role — its EMR cluster + spark
    submit become a TPU slice + per-worker script run). ``keep_alive`` keeps
    the slice after the job like the EMR client's keepClusterAfterExecution.
    """

    def __init__(self, cluster: ClusterProvisioner, keep_alive: bool = False):
        self.cluster = cluster
        self.keep_alive = keep_alive

    def run(self, job_script: str, setup_script: Optional[str] = None) -> List[str]:
        try:
            # inside the try: a PARTIAL create failure must still tear down
            # the workers that did come up (ephemeral semantics)
            self.cluster.create()
            self.cluster.block_till_all_running()
            if setup_script:
                self.cluster.provision_workers(setup_script)
            outs = self.cluster.provision_workers(job_script)
            return outs
        finally:
            if not self.keep_alive:
                self.cluster.teardown()
