"""Cloud provisioning & storage: the reference's AWS module, TPU-native.

The reference ships `deeplearning4j-aws` (EC2 provisioning
`aws/ec2/provision/HostProvisioner.java`, S3 up/download, EMR). The
TPU-native equivalents are GCP: TPU-VM provisioning through ``gcloud`` and
object storage through GCS — with S3 kept for capability parity. Everything
is gated: command builders always work (and are unit-testable); execution
requires the respective CLI/SDK which this image does not bundle, and a
``file://`` scheme provides a local emulation path for tests.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional
from urllib.parse import urlparse


class TpuProvisioner:
    """Builds (and optionally runs) ``gcloud compute tpus tpu-vm`` commands —
    the HostProvisioner role for TPU slices."""

    def __init__(self, project: str, zone: str, runner=None):
        self.project = project
        self.zone = zone
        self._runner = runner or self._run

    @staticmethod
    def _run(cmd: List[str]) -> str:
        if shutil.which(cmd[0]) is None:
            raise RuntimeError(
                f"{cmd[0]!r} CLI not available in this environment; use the "
                "returned command on a workstation with gcloud installed")
        return subprocess.run(cmd, check=True, capture_output=True,
                              text=True).stdout

    def create_command(self, name: str, accelerator_type: str = "v5p-8",
                       version: str = "tpu-ubuntu2204-base") -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "create", name,
                f"--project={self.project}", f"--zone={self.zone}",
                f"--accelerator-type={accelerator_type}",
                f"--version={version}"]

    def delete_command(self, name: str) -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "delete", name,
                f"--project={self.project}", f"--zone={self.zone}", "--quiet"]

    def ssh_command(self, name: str, command: str,
                    worker: str = "all") -> List[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", name,
                f"--project={self.project}", f"--zone={self.zone}",
                f"--worker={worker}", f"--command={command}"]

    def create(self, name: str, **kw) -> str:
        return self._runner(self.create_command(name, **kw))

    def delete(self, name: str) -> str:
        return self._runner(self.delete_command(name))

    def run_on(self, name: str, command: str, **kw) -> str:
        return self._runner(self.ssh_command(name, command, **kw))


class ObjectStorage:
    """Upload/download against gs:// (google-cloud-storage), s3://  (boto3),
    or file:// (always available — the test/emulation path). The reference's
    S3Uploader/S3Downloader role."""

    def upload(self, local_path: str, uri: str) -> None:
        scheme, bucket, key = self._parse(uri)
        if scheme == "file":
            dest = os.path.join(bucket, key.lstrip("/"))
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            shutil.copyfile(local_path, dest)
        elif scheme == "gs":
            client = self._gcs()
            client.bucket(bucket).blob(key.lstrip("/")).upload_from_filename(
                local_path)
        elif scheme == "s3":
            self._s3().upload_file(local_path, bucket, key.lstrip("/"))
        else:
            raise ValueError(f"unsupported scheme {scheme!r}")

    def download(self, uri: str, local_path: str) -> None:
        scheme, bucket, key = self._parse(uri)
        if scheme == "file":
            shutil.copyfile(os.path.join(bucket, key.lstrip("/")), local_path)
        elif scheme == "gs":
            client = self._gcs()
            client.bucket(bucket).blob(key.lstrip("/")).download_to_filename(
                local_path)
        elif scheme == "s3":
            self._s3().download_file(bucket, key.lstrip("/"), local_path)
        else:
            raise ValueError(f"unsupported scheme {scheme!r}")

    @staticmethod
    def _parse(uri: str):
        p = urlparse(uri)
        if p.scheme == "file":
            # file:///tmp/bucket/key → bucket=/tmp/bucket-part? keep it simple:
            # everything up to the last component is the "bucket" directory
            full = p.path
            return "file", os.path.dirname(full), os.path.basename(full)
        return p.scheme, p.netloc, p.path

    @staticmethod
    def _gcs():
        try:
            from google.cloud import storage
        except ImportError as e:
            raise ImportError("google-cloud-storage is not installed; "
                              "use file:// URIs for local staging") from e
        return storage.Client()

    @staticmethod
    def _s3():
        try:
            import boto3
        except ImportError as e:
            raise ImportError("boto3 is not installed; "
                              "use file:// URIs for local staging") from e
        return boto3.client("s3")
