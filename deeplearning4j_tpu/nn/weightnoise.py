"""Weight noise — the ``IWeightNoise`` SPI: transform WEIGHTS (not
activations) at forward time during training.

Reference: ``nn/conf/weightnoise/`` — ``IWeightNoise.java`` (SPI:
``getParameter(layer, paramKey, iteration, epoch, train)``),
``WeightNoise.java`` (additive or multiplicative noise from a configured
Distribution), ``DropConnect.java:19`` (zero each weight with probability
``1 − p``; uses ND4J's plain ``DropOut`` op, i.e. NO inverted rescale —
deliberately matched here).

TPU-first framing: instead of materializing a noised copy of the parameter
table per layer call, the noise is a pure function applied to the param
pytree inside the traced forward — XLA fuses the mask/noise generation into
the consuming matmul, and ``jax.grad`` differentiates through it, which is
exactly DL4J's behavior (gradients flow to the underlying weights).

Applied by the network forward pass when ``layer.weight_noise`` is set and
``train=True``; inference always sees the clean weights.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.weights import Distribution, sample_distribution

Array = jax.Array

WEIGHT_NOISE_REGISTRY: Dict[str, type] = {}


def register_weight_noise(cls):
    WEIGHT_NOISE_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class IWeightNoise:
    """SPI (``weightnoise/IWeightNoise.java``)."""

    apply_to_bias: bool = False

    def apply_param(self, param: Array, rng: jax.Array) -> Array:
        raise NotImplementedError

    def apply(self, layer, params: Dict[str, Array], rng: jax.Array,
              train: bool) -> Dict[str, Array]:
        """Noise the selected entries of one layer's param dict (train only)."""
        if not train or rng is None:
            return params
        names = set(layer.weight_param_names())
        if self.apply_to_bias:
            names |= set(layer.bias_param_names())
        out = {}
        for n, v in params.items():
            if n in names:
                rng, k = jax.random.split(rng)
                out[n] = self.apply_param(v, k)
            else:
                out[n] = v
        return out

    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Distribution):
                v = v.to_dict()
            d[f.name] = v
        d["@weight_noise"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "IWeightNoise":
        d = dict(d)
        cls = WEIGHT_NOISE_REGISTRY[d.pop("@weight_noise")]
        if isinstance(d.get("distribution"), dict):
            d["distribution"] = Distribution.from_dict(d["distribution"])
        return cls(**d)


@register_weight_noise
@dataclasses.dataclass
class WeightNoise(IWeightNoise):
    """Additive (W + n) or multiplicative (W ∘ n) noise drawn fresh each
    forward from ``distribution`` (``WeightNoise.java``)."""

    distribution: Optional[Distribution] = None
    additive: bool = True

    def __post_init__(self):
        if self.distribution is None:
            self.distribution = Distribution(kind="normal", mean=0.0, std=0.01)

    def apply_param(self, param, rng):
        noise = sample_distribution(rng, self.distribution, param.shape,
                                    param.dtype)
        return param + noise if self.additive else param * noise


@register_weight_noise
@dataclasses.dataclass
class DropConnect(IWeightNoise):
    """Zero each weight independently with probability ``1 − p`` at train
    forward time (``DropConnect.java:19``). Matches the reference's plain
    ``DropOut`` op: surviving weights are NOT rescaled by ``1/p``
    (unlike activation :class:`~deeplearning4j_tpu.nn.dropout.Dropout`)."""

    p: float = 0.5

    def __post_init__(self):
        from deeplearning4j_tpu.nn.updaters import Schedule
        if isinstance(self.p, Schedule):
            raise ValueError(
                "DropConnect schedules are not supported (iteration is not "
                "threaded into layer forwards); use a fixed retain prob")
        if not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Weight retain probability must be in (0, 1]: got {self.p}")

    def apply_param(self, param, rng):
        if self.p >= 1.0:
            return param
        keep = jax.random.bernoulli(rng, self.p, param.shape)
        return jnp.where(keep, param, jnp.zeros((), param.dtype))
