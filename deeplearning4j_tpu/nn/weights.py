"""Weight initialization — all 21 DL4J ``WeightInit`` schemes.

Reference: ``deeplearning4j-nn/.../nn/weights/WeightInit.java:68`` and the
variance formulas in ``WeightInitUtil.java``. Fan-in/fan-out are computed from
the layer geometry exactly as DL4J's param initializers do (for conv layers,
fan_in = in_channels * prod(kernel), fan_out = out_channels * prod(kernel)).

Each scheme is ``init(key, shape, fan_in, fan_out, dtype) -> Array``; the
``DISTRIBUTION`` scheme takes a ``Distribution`` spec object.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class Distribution:
    """User-specified distribution for WeightInit.DISTRIBUTION.

    kind: "normal" (mean, std) | "uniform" (lower, upper) |
          "truncated_normal" (mean, std) | "log_normal" (mean, std) |
          "orthogonal" (gain) | "constant" (value) | "binomial" (n, p)
    """

    kind: str = "normal"
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    gain: float = 1.0
    value: float = 0.0
    n: int = 1
    p: float = 0.5

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "Distribution":
        return Distribution(**d)


def _normal(key, shape, std, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.asarray(std, dtype)


def _uniform(key, shape, bound, dtype):
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def _truncated_normal(key, shape, std, dtype):
    # truncation at ±2 std, matching jax.nn.initializers.variance_scaling
    stddev = std / 0.87962566103423978
    return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype) * jnp.asarray(stddev, dtype)


def _identity_matrix(shape, dtype):
    if len(shape) != 2 or shape[0] != shape[1]:
        raise ValueError(f"IDENTITY weight init requires a square 2-D shape, got {shape}")
    return jnp.eye(shape[0], dtype=dtype)


def _orthogonal(key, shape, gain, dtype):
    if len(shape) < 2:
        raise ValueError("orthogonal init needs >=2 dims")
    rows, cols = shape[0], int(math.prod(shape[1:]))
    n = max(rows, cols)
    a = jax.random.normal(key, (n, n), dtype)
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diagonal(r))
    return (gain * q[:rows, :cols]).reshape(shape)


def init_weight(
    key: jax.Array,
    shape: Sequence[int],
    scheme: str,
    fan_in: float,
    fan_out: float,
    dtype=jnp.float32,
    distribution: Optional[Union[Distribution, dict]] = None,
) -> Array:
    """Initialize a weight tensor per the named DL4J scheme."""
    shape = tuple(int(s) for s in shape)
    s = scheme.lower()
    if s == "zero":
        return jnp.zeros(shape, dtype)
    if s == "ones":
        return jnp.ones(shape, dtype)
    if s == "identity":
        return _identity_matrix(shape, dtype)
    if s == "normal":
        # DL4J NORMAL: N(0, 1/sqrt(fanIn))
        return _normal(key, shape, 1.0 / math.sqrt(fan_in), dtype)
    if s == "uniform":
        # DL4J UNIFORM: U(-a, a) with a = 1/sqrt(fanIn)
        return _uniform(key, shape, 1.0 / math.sqrt(fan_in), dtype)
    if s == "xavier":
        return _normal(key, shape, math.sqrt(2.0 / (fan_in + fan_out)), dtype)
    if s == "xavier_uniform":
        return _uniform(key, shape, math.sqrt(6.0 / (fan_in + fan_out)), dtype)
    if s == "xavier_fan_in":
        return _normal(key, shape, math.sqrt(1.0 / fan_in), dtype)
    if s == "xavier_legacy":
        return _normal(key, shape, 1.0 / math.sqrt(shape[0] + shape[-1]), dtype)
    if s == "sigmoid_uniform":
        return _uniform(key, shape, 4.0 * math.sqrt(6.0 / (fan_in + fan_out)), dtype)
    if s == "relu":
        return _normal(key, shape, math.sqrt(2.0 / fan_in), dtype)
    if s == "relu_uniform":
        return _uniform(key, shape, math.sqrt(6.0 / fan_in), dtype)
    if s == "lecun_normal":
        return _normal(key, shape, math.sqrt(1.0 / fan_in), dtype)
    if s == "lecun_uniform":
        return _uniform(key, shape, math.sqrt(3.0 / fan_in), dtype)
    if s == "var_scaling_normal_fan_in":
        return _truncated_normal(key, shape, math.sqrt(1.0 / fan_in), dtype)
    if s == "var_scaling_normal_fan_out":
        return _truncated_normal(key, shape, math.sqrt(1.0 / fan_out), dtype)
    if s == "var_scaling_normal_fan_avg":
        return _truncated_normal(key, shape, math.sqrt(2.0 / (fan_in + fan_out)), dtype)
    if s == "var_scaling_uniform_fan_in":
        return _uniform(key, shape, math.sqrt(3.0 / fan_in), dtype)
    if s == "var_scaling_uniform_fan_out":
        return _uniform(key, shape, math.sqrt(3.0 / fan_out), dtype)
    if s == "var_scaling_uniform_fan_avg":
        return _uniform(key, shape, math.sqrt(6.0 / (fan_in + fan_out)), dtype)
    if s == "distribution":
        if distribution is None:
            raise ValueError("WeightInit DISTRIBUTION requires a Distribution spec")
        return sample_distribution(key, distribution, shape, dtype)
    raise ValueError(f"Unknown weight init scheme {scheme!r}")


def sample_distribution(key: jax.Array,
                        distribution: Union[Distribution, dict],
                        shape: Sequence[int], dtype=jnp.float32) -> Array:
    """Draw a tensor from a :class:`Distribution` spec (the sampling half of
    WeightInit.DISTRIBUTION, also used by weight noise)."""
    if isinstance(distribution, dict):
        distribution = Distribution.from_dict(distribution)
    d = distribution
    shape = tuple(int(s) for s in shape)
    if d.kind == "normal":
        return d.mean + _normal(key, shape, d.std, dtype)
    if d.kind == "truncated_normal":
        return d.mean + _truncated_normal(key, shape, d.std, dtype)
    if d.kind == "log_normal":
        return jnp.exp(d.mean + _normal(key, shape, d.std, dtype))
    if d.kind == "uniform":
        return jax.random.uniform(key, shape, dtype, minval=d.lower, maxval=d.upper)
    if d.kind == "orthogonal":
        return _orthogonal(key, shape, d.gain, dtype)
    if d.kind == "constant":
        return jnp.full(shape, d.value, dtype)
    if d.kind == "binomial":
        return jax.random.binomial(key, d.n, d.p, shape).astype(dtype)
    raise ValueError(f"Unknown distribution kind {d.kind!r}")


ALL_SCHEMES = [
    "distribution", "zero", "ones", "sigmoid_uniform", "normal", "lecun_normal",
    "uniform", "xavier", "xavier_uniform", "xavier_fan_in", "xavier_legacy",
    "relu", "relu_uniform", "identity", "lecun_uniform",
    "var_scaling_normal_fan_in", "var_scaling_normal_fan_out",
    "var_scaling_normal_fan_avg", "var_scaling_uniform_fan_in",
    "var_scaling_uniform_fan_out", "var_scaling_uniform_fan_avg",
]
