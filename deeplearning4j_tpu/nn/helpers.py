"""Native acceleration helper seam.

Parity with the reference's L1 helper layer (SURVEY.md §1): five helper
interfaces (`ConvolutionHelper.java:35`, `SubsamplingHelper.java:31`,
`LSTMHelper.java:34`, `BatchNormalizationHelper.java:29`,
`LocalResponseNormalizationHelper.java:29`) loaded reflectively by the layer
implementations (`ConvolutionLayer.java:76-84`) so cuDNN can replace the
built-in math. Here the default math IS the compiled fast path (XLA), so
helpers are **opt-in Pallas kernels** registered per kind; layers consult the
registry exactly like the reference's reflective load, and un-registering
restores stock XLA. The validation contract is the reference's too: a helper
must produce the same numbers as the built-in path (`ValidateCudnnLSTM.java`
pattern — see tests/test_helpers.py).
"""

from __future__ import annotations

from typing import Dict, Optional

_HELPERS: Dict[str, object] = {}
_VERSION = 0  # bumped on every registry change; part of every jit cache key

KINDS = ("lstm", "convolution", "subsampling", "batch_norm", "lrn",
         "attention", "updater")


def evict_stale_jit_entries(cache: Dict, current_version: int) -> None:
    """Drop jit-cache entries compiled under an older registry version
    (version-suffixed tuple keys). Shared by MultiLayerNetwork and
    ComputationGraph so the eviction rule lives in one place."""
    for k in [k for k in cache
              if isinstance(k, tuple) and k[-1] != current_version]:
        del cache[k]


def version() -> int:
    """Registry generation. Networks include this in their jit cache keys so
    set/clear AFTER a network has compiled still takes effect on the next
    call (the registry is consulted at trace time)."""
    return _VERSION


def set_helper(kind: str, helper) -> None:
    global _VERSION
    if kind not in KINDS:
        raise ValueError(f"unknown helper kind {kind!r} (expected one of {KINDS})")
    _HELPERS[kind] = helper
    _VERSION += 1


def get_helper(kind: str):
    return _HELPERS.get(kind)


def clear_helper(kind: str) -> None:
    global _VERSION
    if _HELPERS.pop(kind, None) is not None:
        _VERSION += 1


def clear_all_helpers() -> None:
    global _VERSION
    if _HELPERS:
        _VERSION += 1
    _HELPERS.clear()


# -- flash-attention auto-registration ---------------------------------------
# When NO attention helper is registered, causal attention at T >= 2048 on a
# TPU backend automatically uses the causal PallasFlashAttentionHelper — the
# measured win region (LM training 1.45x at T=2048, 2.64x at T=4096; the
# kernel skips the masked upper triangle the einsum path still computes).
# Registering any helper, or set_auto_flash_attention(False), overrides.
_AUTO_FLASH = True


def set_auto_flash_attention(enabled: bool) -> None:
    """Opt out of (or back into) the automatic causal-flash fallback.
    Bumps the registry version so already-compiled networks retrace."""
    global _AUTO_FLASH, _VERSION
    if _AUTO_FLASH != bool(enabled):
        _AUTO_FLASH = bool(enabled)
        _VERSION += 1


def auto_flash_attention_enabled() -> bool:
    return _AUTO_FLASH


# -- fused-LSTM auto-registration ---------------------------------------------
# When NO lstm helper is registered, a standard LSTM on a TPU backend in the
# fused kernel's win region (see layers/recurrent.py:_AUTO_LSTM_MIN_T)
# automatically uses PallasLSTMHelper — same promotion pattern as the causal
# flash fallback above. Registering any lstm helper, or
# set_auto_fused_lstm(False), overrides.
_AUTO_LSTM = True


def set_auto_fused_lstm(enabled: bool) -> None:
    """Opt out of (or back into) the automatic fused-LSTM fallback.
    Bumps the registry version so already-compiled networks retrace."""
    global _AUTO_LSTM, _VERSION
    if _AUTO_LSTM != bool(enabled):
        _AUTO_LSTM = bool(enabled)
        _VERSION += 1


def auto_fused_lstm_enabled() -> bool:
    return _AUTO_LSTM


class LSTMHelper:
    """Interface (`LSTMHelper.java:34`): accelerate the LSTM sequence pass."""

    def supports(self, layer, mask) -> bool:  # pragma: no cover - interface
        return False

    def forward_seq(self, layer, params, x, carry):  # pragma: no cover
        raise NotImplementedError


class UpdaterHelper:
    """Interface for fused optimizer-update kernels (the role ND4J's native
    updater ops play under ``UpdaterBlock.update``). ``apply`` performs the
    WHOLE read-modify-write for one parameter tensor — new param AND new
    updater state — so a kernel implementation can fuse the per-param
    elementwise chain into one launch over donated buffers.

    ``_apply_updates`` consults the seam per parameter at trace time; the
    registry version is part of every train-step jit cache key, so
    registration after compile retraces (same contract as the layer kinds).
    A helper must only accept (``supports``) updaters whose math it
    reproduces within the equivalence tolerance of tests/test_helpers.py."""

    def supports(self, updater, param, grad) -> bool:  # pragma: no cover
        return False

    def apply(self, updater, param, grad, state, lr, t):  # pragma: no cover
        """Returns ``(new_param, new_state)`` for one parameter tensor."""
        raise NotImplementedError


class AttentionHelper:
    """Interface for fused attention kernels (no reference counterpart —
    the snapshot predates attention; same seam pattern as the cuDNN five).

    ``causal`` describes the REQUESTED semantics: a helper must only accept
    a request whose causality matches what its ``attend`` computes, so
    registering any helper can never change model outputs."""

    def supports(self, layer, q_shape, mask, dropout_active,
                 causal=False) -> bool:  # pragma: no cover - interface
        return False

    def attend(self, q, k, v):  # pragma: no cover - interface
        raise NotImplementedError
