"""Parameter constraints applied AFTER each update, inside the jitted step.

Reference: ``nn/conf/constraint/`` — ``BaseConstraint.java`` (LayerConstraint
SPI, per-param-name application), ``MaxNormConstraint.java:21``,
``MinMaxNormConstraint.java``, ``NonNegativeConstraint.java``,
``UnitNormConstraint.java``. Set per-layer (``constraints=[...]``) or via the
network builder (``constrain_weights`` / ``constrain_bias`` /
``constrain_all_parameters``), exactly like the DL4J builder hooks
(``NeuralNetConfiguration.java:1031-1060``).

TPU-first framing: a constraint is a pure array→array projection composed
onto the parameter after the updater's delta, so it fuses into the one
donated-buffer train step — no post-step host round trip.

Norm ``dimensions`` are the REDUCTION axes of the L2 norm. ``None`` (the
default) reduces over all axes except the last, which for this framework's
layouts (Dense ``[n_in, n_out]``, conv ``[kh, kw, in, out]``) is the norm of
the incoming weights of each output unit — the same quantity DL4J's
"dimension 1 on [nIn, nOut]" and Keras's default ``axis=0`` compute.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

CONSTRAINT_REGISTRY: Dict[str, type] = {}

DEFAULT_EPSILON = 1e-6  # BaseConstraint.DEFAULT_EPSILON


def register_constraint(cls):
    CONSTRAINT_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class LayerConstraint:
    """SPI (``nn/api/layers/LayerConstraint.java`` role).

    ``param_names``: explicit parameter names to constrain; when ``None``
    the ``scope`` picks them from the layer ("weights" | "bias" | "all" —
    the three DL4J builder hooks).
    """

    param_names: Optional[Tuple[str, ...]] = None
    scope: str = "weights"
    dimensions: Optional[Tuple[int, ...]] = None

    # -- application -------------------------------------------------------
    def apply(self, param: Array) -> Array:
        raise NotImplementedError

    def apply_to(self, layer, params: Dict[str, Array]) -> Dict[str, Array]:
        """Constrain the selected entries of one layer's param dict."""
        if self.param_names is not None:
            names = set(self.param_names)
        elif self.scope == "all":
            names = set(params)
        elif self.scope == "bias":
            names = set(layer.bias_param_names())
        else:
            names = set(layer.weight_param_names())
        return {n: (self.apply(v) if n in names else v)
                for n, v in params.items()}

    def scoped(self, scope: str) -> "LayerConstraint":
        return dataclasses.replace(self, scope=scope)

    # -- norm helper -------------------------------------------------------
    def _axes(self, param: Array) -> Tuple[int, ...]:
        if self.dimensions is not None:
            return tuple(int(d) for d in self.dimensions)
        return tuple(range(max(param.ndim - 1, 1)))  # all but last (≥1 axis)

    def _norm2(self, param: Array) -> Array:
        axes = self._axes(param)
        if param.ndim == 1:
            axes = (0,)
        return jnp.sqrt(jnp.sum(jnp.square(param), axis=axes, keepdims=True))

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        d = {k: v for k, v in dataclasses.asdict(self).items() if v is not None}
        for k in ("param_names", "dimensions"):
            if k in d:
                d[k] = list(d[k])
        d["@constraint"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "LayerConstraint":
        d = dict(d)
        cls = CONSTRAINT_REGISTRY[d.pop("@constraint")]
        for k in ("param_names", "dimensions"):
            if isinstance(d.get(k), list):
                d[k] = tuple(d[k])
        return cls(**d)


@register_constraint
@dataclasses.dataclass
class MaxNormConstraint(LayerConstraint):
    """Scale down any unit whose incoming-weight L2 norm exceeds ``max_norm``
    (``MaxNormConstraint.java:21``: norm2 over dims, clip, rescale)."""

    max_norm: float = 1.0

    def apply(self, param: Array) -> Array:
        norm = self._norm2(param)
        clipped = jnp.minimum(norm, self.max_norm)
        return param * (clipped / (norm + DEFAULT_EPSILON))


@register_constraint
@dataclasses.dataclass
class MinMaxNormConstraint(LayerConstraint):
    """Constrain incoming-weight norms into ``[min_norm, max_norm]``
    (``MinMaxNormConstraint.java``). ``rate`` blends toward the projection,
    Keras ``min_max_norm`` style: scale = rate·clip(n)/(n+ε) + (1−rate)."""

    min_norm: float = 0.0
    max_norm: float = 1.0
    rate: float = 1.0

    def __post_init__(self):
        if not (0.0 < self.rate <= 1.0):
            raise ValueError(f"Invalid rate: must be in (0, 1]: got {self.rate}")

    def apply(self, param: Array) -> Array:
        norm = self._norm2(param)
        clipped = jnp.clip(norm, self.min_norm, self.max_norm)
        scale = clipped / (norm + DEFAULT_EPSILON)
        if self.rate != 1.0:
            scale = self.rate * scale + (1.0 - self.rate)
        return param * scale


@register_constraint
@dataclasses.dataclass
class UnitNormConstraint(LayerConstraint):
    """Force incoming-weight norms to exactly 1 (``UnitNormConstraint.java``:
    divide by norm2)."""

    def apply(self, param: Array) -> Array:
        return param / (self._norm2(param) + DEFAULT_EPSILON)


@register_constraint
@dataclasses.dataclass
class NonNegativeConstraint(LayerConstraint):
    """Clamp negatives to zero (``NonNegativeConstraint.java``)."""

    def apply(self, param: Array) -> Array:
        return jnp.maximum(param, 0.0)


def apply_constraints(layer, params: Dict[str, Array]) -> Dict[str, Array]:
    """Run a layer's configured constraint chain over its updated params
    (the post-update hook ``BaseConstraint.applyConstraint`` runs at
    ``MultiLayerNetwork``/``ComputationGraph`` iteration end).

    Wrapper layers (LastTimeStep/TimeDistributed/Bidirectional/Frozen) carry
    no constraints of their own — the chain configured on their INNER layer
    applies to the wrapper's param dict (Bidirectional stores two ``f_``/
    ``b_``-prefixed copies of the inner params; both halves are constrained).
    """
    cs = getattr(layer, "constraints", None)
    if not cs:
        inner = getattr(layer, "layer", None)
        if (inner is not None and getattr(inner, "constraints", None)
                and params):
            if all(k.startswith(("f_", "b_")) for k in params):
                halves = {}
                for pre in ("f_", "b_"):
                    sub = {k[len(pre):]: v for k, v in params.items()
                           if k.startswith(pre)}
                    sub = apply_constraints(inner, sub)
                    halves.update({pre + k: v for k, v in sub.items()})
                return halves
            return apply_constraints(inner, params)
        return params
    for c in cs:
        params = c.apply_to(layer, params)
    return params


def constraints_from_config(v):
    """Deserialize a layer's ``constraints`` field (list of tagged dicts)."""
    if v is None:
        return None
    return [c if isinstance(c, LayerConstraint) else LayerConstraint.from_dict(c)
            for c in v]
