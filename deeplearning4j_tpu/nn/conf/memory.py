"""Memory estimation: analytic per-layer forecasts + compiled-HLO analysis.

Parity with the reference's ``nn/conf/memory/`` package
(`MemoryReport.java:70`, `LayerMemoryReport.java`, `NetworkMemoryReport.java`,
`MemoryType.java`, `MemoryUseMode.java`): analytic, pre-run forecasts of
parameter / gradient / updater-state / activation memory per layer and per
network, JSON-serialisable.

TPU addition the reference cannot offer: :func:`compiled_memory_analysis` asks
XLA for the *actual* buffer assignment of the jitted training step
(``lowered.compile().memory_analysis()``) — exact HBM numbers (arguments,
outputs, temps, generated code) instead of an estimate.
"""

from __future__ import annotations

import enum
import json
from typing import Dict, List, Optional

import numpy as np


class MemoryType(enum.Enum):
    """What a block of memory is used for (``MemoryType.java``)."""

    PARAMETERS = "parameters"
    PARAMETER_GRADIENTS = "parameter_gradients"
    ACTIVATIONS = "activations"
    ACTIVATION_GRADIENTS = "activation_gradients"
    UPDATER_STATE = "updater_state"
    WORKING_MEMORY_FIXED = "working_memory_fixed"
    WORKING_MEMORY_VARIABLE = "working_memory_variable"

    def is_inference(self) -> bool:
        """Types that exist during inference as well as training
        (``MemoryType.java:16-25``)."""
        return self in (MemoryType.PARAMETERS, MemoryType.ACTIVATIONS,
                        MemoryType.WORKING_MEMORY_FIXED,
                        MemoryType.WORKING_MEMORY_VARIABLE)


class MemoryUseMode(enum.Enum):
    TRAINING = "training"
    INFERENCE = "inference"


# updater classname -> number of state copies of the params it keeps
_UPDATER_STATE_MULT = {
    "Sgd": 0, "NoOp": 0,
    "Nesterovs": 1, "AdaGrad": 1, "RmsProp": 1,
    "Adam": 2, "AdaMax": 2, "AdaDelta": 2, "Nadam": 2,
    "AMSGrad": 3,
}


def updater_state_multiplier(updater) -> int:
    return _UPDATER_STATE_MULT.get(type(updater).__name__, 2)


class LayerMemoryReport:
    """Per-layer memory forecast (``LayerMemoryReport.java``): fixed counts
    (params, updater state) and per-example counts (activations, working
    memory), in *elements*; byte totals computed against a minibatch size and
    dtype width."""

    def __init__(self, layer_name: str, layer_type: str, *, parameters: int = 0,
                 updater_state: int = 0, activations_per_ex: int = 0,
                 working_mem_fixed: int = 0, working_mem_per_ex: int = 0):
        self.layer_name = layer_name
        self.layer_type = layer_type
        self.parameters = int(parameters)
        self.updater_state = int(updater_state)
        self.activations_per_ex = int(activations_per_ex)
        self.working_mem_fixed = int(working_mem_fixed)
        self.working_mem_per_ex = int(working_mem_per_ex)

    def get_memory_elements(self, memory_type: MemoryType, minibatch: int,
                            mode: MemoryUseMode = MemoryUseMode.TRAINING) -> int:
        training = mode is MemoryUseMode.TRAINING
        if memory_type is MemoryType.PARAMETERS:
            return self.parameters
        if memory_type is MemoryType.PARAMETER_GRADIENTS:
            return self.parameters if training else 0
        if memory_type is MemoryType.ACTIVATIONS:
            return self.activations_per_ex * minibatch
        if memory_type is MemoryType.ACTIVATION_GRADIENTS:
            return self.activations_per_ex * minibatch if training else 0
        if memory_type is MemoryType.UPDATER_STATE:
            return self.updater_state if training else 0
        if memory_type is MemoryType.WORKING_MEMORY_FIXED:
            return self.working_mem_fixed
        if memory_type is MemoryType.WORKING_MEMORY_VARIABLE:
            return self.working_mem_per_ex * minibatch
        return 0

    def get_total_memory_bytes(self, minibatch: int,
                               mode: MemoryUseMode = MemoryUseMode.TRAINING,
                               bytes_per_element: int = 4) -> int:
        return sum(self.get_memory_elements(t, minibatch, mode)
                   for t in MemoryType) * bytes_per_element

    def to_dict(self) -> dict:
        return {"layer_name": self.layer_name, "layer_type": self.layer_type,
                "parameters": self.parameters,
                "updater_state": self.updater_state,
                "activations_per_ex": self.activations_per_ex,
                "working_mem_fixed": self.working_mem_fixed,
                "working_mem_per_ex": self.working_mem_per_ex}

    @staticmethod
    def from_dict(d: dict) -> "LayerMemoryReport":
        return LayerMemoryReport(d["layer_name"], d["layer_type"],
                                 parameters=d["parameters"],
                                 updater_state=d["updater_state"],
                                 activations_per_ex=d["activations_per_ex"],
                                 working_mem_fixed=d.get("working_mem_fixed", 0),
                                 working_mem_per_ex=d.get("working_mem_per_ex", 0))


class NetworkMemoryReport:
    """Whole-network forecast: aggregates layer reports
    (``NetworkMemoryReport.java:26``)."""

    def __init__(self, layer_reports: List[LayerMemoryReport], model_name: str,
                 input_elements_per_ex: int = 0, bytes_per_element: int = 4):
        self.layer_reports = list(layer_reports)
        self.model_name = model_name
        self.input_elements_per_ex = int(input_elements_per_ex)
        self.bytes_per_element = bytes_per_element

    def get_name(self) -> str:
        return self.model_name

    def get_memory_bytes(self, memory_type: MemoryType, minibatch: int,
                         mode: MemoryUseMode = MemoryUseMode.TRAINING) -> int:
        total = sum(r.get_memory_elements(memory_type, minibatch, mode)
                    for r in self.layer_reports)
        if memory_type is MemoryType.ACTIVATIONS:
            total += self.input_elements_per_ex * minibatch
        return total * self.bytes_per_element

    def get_total_memory_bytes(self, minibatch: int,
                               mode: MemoryUseMode = MemoryUseMode.TRAINING) -> int:
        return sum(self.get_memory_bytes(t, minibatch, mode) for t in MemoryType)

    def to_json(self) -> str:
        return json.dumps({
            "model_name": self.model_name,
            "bytes_per_element": self.bytes_per_element,
            "input_elements_per_ex": self.input_elements_per_ex,
            "layers": [r.to_dict() for r in self.layer_reports],
        })

    @staticmethod
    def from_json(s: str) -> "NetworkMemoryReport":
        d = json.loads(s)
        return NetworkMemoryReport(
            [LayerMemoryReport.from_dict(r) for r in d["layers"]],
            d["model_name"], d.get("input_elements_per_ex", 0),
            d.get("bytes_per_element", 4))

    def __str__(self) -> str:
        lines = [f"NetworkMemoryReport: {self.model_name} "
                 f"({len(self.layer_reports)} layers)"]
        header = f"  {'layer':<24}{'type':<26}{'params':>12}{'act/ex':>10}"
        lines.append(header)
        for r in self.layer_reports:
            lines.append(f"  {r.layer_name:<24}{r.layer_type:<26}"
                         f"{r.parameters:>12}{r.activations_per_ex:>10}")
        for mb in (1, 32):
            tot = self.get_total_memory_bytes(mb)
            lines.append(f"  total training memory @ batch {mb}: "
                         f"{tot / (1 << 20):.2f} MiB")
        return "\n".join(lines)


def network_memory_report(conf, model_name: str = "MultiLayerNetwork") -> NetworkMemoryReport:
    """Build a NetworkMemoryReport from a finalized MultiLayerConfiguration
    (the reference builds these via ``getMemoryReport(InputType)``)."""
    import math

    bytes_per = 4 if conf.global_conf.dtype in ("float32",) else (
        8 if conf.global_conf.dtype == "float64" else 2)
    reports = []
    for i, l in enumerate(conf.layers):
        n_params = l.num_params()
        act = 0
        if conf.input_type is not None and conf.layer_input_types[i] is not None:
            out = l.output_type(conf.layer_input_types[i])
            act = int(math.prod(out.batch_shape(1)))
        upd = getattr(l, "updater", None) or conf.global_conf.updater
        mult = updater_state_multiplier(upd) if upd is not None else 0
        reports.append(LayerMemoryReport(
            l.name or f"layer{i}", type(l).__name__,
            parameters=n_params, updater_state=n_params * mult,
            activations_per_ex=act))
    in_elems = 0
    if conf.input_type is not None:
        in_elems = int(math.prod(conf.input_type.batch_shape(1)))
    return NetworkMemoryReport(reports, model_name, in_elems, bytes_per)


def compiled_memory_analysis(net, batch: int = 32) -> Dict[str, int]:
    """Exact memory numbers from XLA's buffer assignment for the jitted
    training step — measured, not estimated. Returns byte counts
    (``argument_size``, ``output_size``, ``temp_size``, ``alias_size``,
    ``generated_code_size``) plus ``total``."""
    import jax
    import jax.numpy as jnp

    if net.params is None:
        net.init()
    if net.conf.input_type is None:
        raise ValueError("compiled_memory_analysis requires the configuration "
                         "to have an input type (set_input_type(...)) so the "
                         "step can be traced with concrete shapes")
    dtype = net.conf.global_conf.jnp_dtype()
    in_shape = net.conf.input_type.batch_shape(batch)
    out_type = net.conf.output_type()
    out_shape = out_type.batch_shape(batch)
    x = jnp.zeros(in_shape, dtype)
    y = jnp.zeros(out_shape, dtype)

    def step(params, upd_states, x, y):
        def lf(p):
            loss, _ = net._loss_fn(p, net.states, x, y, None, None, None,
                                   train=True)
            return loss
        loss, grads = jax.value_and_grad(lf)(params)
        new_params, new_upd = net._apply_updates(
            params, grads, upd_states, jnp.float32(0), jnp.float32(0))
        return new_params, new_upd, loss

    lowered = jax.jit(step).lower(net.params, net.updater_states, x, y)
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    if ma is None:  # backend without memory analysis
        return {}
    out = {
        "argument_size": int(getattr(ma, "argument_size_in_bytes", 0)),
        "output_size": int(getattr(ma, "output_size_in_bytes", 0)),
        "temp_size": int(getattr(ma, "temp_size_in_bytes", 0)),
        "alias_size": int(getattr(ma, "alias_size_in_bytes", 0)),
        "generated_code_size": int(getattr(ma, "generated_code_size_in_bytes", 0)),
    }
    out["total"] = (out["argument_size"] + out["output_size"]
                    + out["temp_size"] + out["generated_code_size"])
    return out
