"""ComputationGraphConfiguration + GraphBuilder.

Reference: ``nn/conf/ComputationGraphConfiguration.java:547`` (GraphBuilder):
named inputs, layer/vertex nodes with named input edges, named outputs,
InputType propagation through the DAG, JSON serde. Topological order is
computed once at build time (the reference caches it at
``ComputationGraph.topologicalOrder:152``).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple, Union

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import GlobalConf, normalize_backprop_type
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.updaters import Updater
from deeplearning4j_tpu.nn.vertices import GraphVertex
from deeplearning4j_tpu.nn.weights import Distribution


@dataclasses.dataclass
class VertexDef:
    """One DAG node: a Layer (has params) or a GraphVertex (pure function)."""

    name: str
    obj: Union[Layer, GraphVertex]
    inputs: List[str]

    @property
    def is_layer(self) -> bool:
        return isinstance(self.obj, Layer)


class GraphBuilder:
    """Fluent DAG builder (ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, VertexDef] = {}
        self._input_types: List[Optional[InputType]] = []
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"duplicate vertex name {name!r}")
        layer.name = layer.name or name
        self._vertices[name] = VertexDef(name, layer, list(inputs))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"duplicate vertex name {name!r}")
        self._vertices[name] = VertexDef(name, vertex, list(inputs))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        self._backprop_type = normalize_backprop_type(t)
        return self

    def t_bptt_length(self, fwd: int, bwd: Optional[int] = None) -> "GraphBuilder":
        self._tbptt_fwd = fwd
        self._tbptt_bwd = bwd if bwd is not None else fwd
        self._backprop_type = "truncated_bptt"
        return self

    def build(self) -> "ComputationGraphConfiguration":
        conf = ComputationGraphConfiguration(
            global_conf=self._g,
            inputs=list(self._inputs),
            outputs=list(self._outputs),
            vertices=dict(self._vertices),
            input_types=list(self._input_types),
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
        )
        conf.finalize()
        return conf


@dataclasses.dataclass
class ComputationGraphConfiguration:
    global_conf: GlobalConf
    inputs: List[str]
    outputs: List[str]
    vertices: Dict[str, VertexDef]
    input_types: List[Optional[InputType]] = dataclasses.field(default_factory=list)
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    # computed by finalize():
    topo_order: List[str] = dataclasses.field(default_factory=list)
    preprocessors: Dict[str, object] = dataclasses.field(default_factory=dict)
    vertex_input_types: Dict[str, List[InputType]] = dataclasses.field(default_factory=dict)
    _finalized: bool = False

    def __post_init__(self):
        self.backprop_type = normalize_backprop_type(self.backprop_type)

    # ------------------------------------------------------------- finalize
    def finalize(self) -> None:
        if self._finalized:
            return
        if not self.inputs:
            raise ValueError("graph has no inputs")
        if not self.outputs:
            raise ValueError("graph has no outputs")
        for name, vd in self.vertices.items():
            for src in vd.inputs:
                if src not in self.vertices and src not in self.inputs:
                    raise ValueError(f"vertex {name!r} references unknown input {src!r}")
        for out in self.outputs:
            if out not in self.vertices:
                raise ValueError(f"output {out!r} is not a vertex")
        self._topo_sort()
        for vd in self.vertices.values():
            if vd.is_layer:
                vd.obj.apply_global_defaults(self.global_conf)  # type: ignore[arg-type]
        if self.input_types and all(t is not None for t in self.input_types):
            self._infer_types()
        self._finalized = True

    def _topo_sort(self) -> None:
        """Kahn's algorithm (ComputationGraph.topologicalSortOrder():1211)."""
        indeg = {n: 0 for n in self.vertices}
        dependents: Dict[str, List[str]] = {n: [] for n in self.vertices}
        for name, vd in self.vertices.items():
            for src in vd.inputs:
                if src in self.vertices:
                    indeg[name] += 1
                    dependents[src].append(name)
        ready = sorted(n for n, d in indeg.items() if d == 0)
        order: List[str] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for m in sorted(dependents[n]):
                indeg[m] -= 1
                if indeg[m] == 0:
                    ready.append(m)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"graph has a cycle involving {sorted(cyc)}")
        self.topo_order = order

    def _infer_types(self) -> None:
        if len(self.input_types) != len(self.inputs):
            raise ValueError("set_input_types needs one InputType per input")
        types: Dict[str, InputType] = dict(zip(self.inputs, self.input_types))
        for name in self.topo_order:
            vd = self.vertices[name]
            in_types = [types[src] for src in vd.inputs]
            self.vertex_input_types[name] = in_types
            if vd.is_layer:
                layer: Layer = vd.obj  # type: ignore[assignment]
                if getattr(layer, "consumes_multiple_inputs", False):
                    # multi-input layers (e.g. cross-attention) see every
                    # input type separately — no concat, no preprocessor
                    layer.set_n_in_multi(in_types)
                    types[name] = layer.output_type_multi(in_types)
                    continue
                it = in_types[0]
                pre = layer.input_preprocessor(it)
                if pre is not None:
                    fn, it = pre
                    self.preprocessors[name] = fn
                layer.set_n_in(it)
                types[name] = layer.output_type(it)
            else:
                types[name] = vd.obj.output_type(in_types)  # type: ignore[union-attr]

    # -------------------------------------------------------- introspection
    def layer_vertices(self) -> List[VertexDef]:
        return [self.vertices[n] for n in self.topo_order if self.vertices[n].is_layer]

    def num_params(self) -> int:
        return sum(vd.obj.num_params() for vd in self.layer_vertices())

    # ---------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        from deeplearning4j_tpu.nn.conf.network import global_conf_to_dict
        g = global_conf_to_dict(self.global_conf)
        return {
            "format": "deeplearning4j_tpu.ComputationGraphConfiguration",
            "version": 1,
            "global": g,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "vertices": [
                {"name": vd.name, "inputs": vd.inputs, "def": vd.obj.to_dict()}
                for vd in (self.vertices[n] for n in self.topo_order)
            ],
            "input_types": [None if t is None else t.to_dict()
                            for t in self.input_types],
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_bwd_length": self.tbptt_bwd_length,
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.conf.network import global_conf_from_dict
        vertices: Dict[str, VertexDef] = {}
        for vd in d["vertices"]:
            obj_d = vd["def"]
            obj = (layer_from_dict(obj_d) if "@layer" in obj_d
                   else GraphVertex.from_dict(obj_d))
            vertices[vd["name"]] = VertexDef(vd["name"], obj, list(vd["inputs"]))
        conf = ComputationGraphConfiguration(
            global_conf=global_conf_from_dict(d["global"]),
            inputs=list(d["inputs"]),
            outputs=list(d["outputs"]),
            vertices=vertices,
            input_types=[None if t is None else InputType.from_dict(t)
                         for t in d.get("input_types", [])],
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_bwd_length=d.get("tbptt_bwd_length", 20),
        )
        conf.finalize()
        return conf

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self, **kw) -> str:
        """YAML form of the same serde dict (``ComputationGraphConfiguration
        .toYaml``)."""
        import json as _json

        import yaml
        return yaml.safe_dump(_json.loads(self.to_json()), sort_keys=False,
                              **kw)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))
