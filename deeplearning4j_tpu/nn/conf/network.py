"""Network configuration DSL — NeuralNetConfiguration / MultiLayerConfiguration.

Reference: ``nn/conf/NeuralNetConfiguration.java:578`` (Builder) and ``:203,738``
(ListBuilder / ``list()``), ``MultiLayerConfiguration.java``. The fluent
builder produces an immutable JSON-serializable configuration; global training
hyperparameters flow into layers that didn't override them; InputType
inference sets each layer's n_in and inserts automatic reshape preprocessors
(DL4J's ``InputPreProcessor`` system).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, List, Optional, Sequence, Union

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.updaters import Updater, resolve_updater
from deeplearning4j_tpu.nn.weights import Distribution


@dataclasses.dataclass
class GlobalConf:
    """Global (per-network) defaults, inherited by layers (DL4J Builder fields)."""

    seed: int = 12345
    activation: Optional[str] = None
    weight_init: Optional[str] = "xavier"
    distribution: Optional[Distribution] = None
    bias_init: Optional[float] = 0.0
    updater: Optional[Updater] = None
    bias_updater: Optional[Updater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[Any] = None  # float keep-prob or IDropout
    weight_noise: Optional[Any] = None  # IWeightNoise (WeightNoise/DropConnect)
    # builder-level constraints, attached to every layer at finalize()
    # (NeuralNetConfiguration.java:1031-1060)
    all_constraints: Optional[List[Any]] = None
    weight_constraints: Optional[List[Any]] = None
    bias_constraints: Optional[List[Any]] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    mini_batch: bool = True
    dtype: str = "float32"
    # mixed precision: params/updater state stay in `dtype`; forward/backward
    # compute is cast to this (e.g. "bfloat16" → MXU fast path, f32 master
    # weights). None = single-precision throughout.
    compute_dtype: Optional[str] = None
    # rematerialization: recompute layer activations in the backward pass
    # instead of storing them (jax.checkpoint per layer) — trades FLOPs for
    # HBM, the workspace/memory-strategy lever for deep nets
    gradient_checkpointing: bool = False
    optimization_algo: str = "stochastic_gradient_descent"
    max_num_line_search_iterations: int = 5

    def jnp_dtype(self):
        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                "float16": jnp.float16, "float64": jnp.float64}[self.dtype]

    def jnp_compute_dtype(self):
        if self.compute_dtype is None:
            return None
        return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                "float16": jnp.float16}[self.compute_dtype]


def global_conf_to_dict(gc: GlobalConf) -> dict:
    """Serialize a GlobalConf, tagging the nested spec objects."""
    from deeplearning4j_tpu.nn.dropout import IDropout
    g = dataclasses.asdict(gc)
    if gc.updater is not None:
        g["updater"] = gc.updater.to_dict()
    if gc.bias_updater is not None:
        g["bias_updater"] = gc.bias_updater.to_dict()
    if gc.distribution is not None:
        g["distribution"] = gc.distribution.to_dict()
    if isinstance(gc.dropout, IDropout):
        g["dropout"] = gc.dropout.to_dict()
    if gc.weight_noise is not None:
        g["weight_noise"] = gc.weight_noise.to_dict()
    for key in ("all_constraints", "weight_constraints", "bias_constraints"):
        v = getattr(gc, key)
        if v:
            g[key] = [c.to_dict() for c in v]
    return g


def global_conf_from_dict(d: dict) -> GlobalConf:
    from deeplearning4j_tpu.nn.constraints import LayerConstraint
    from deeplearning4j_tpu.nn.dropout import IDropout
    from deeplearning4j_tpu.nn.weightnoise import IWeightNoise
    g = dict(d)
    if isinstance(g.get("updater"), dict):
        g["updater"] = Updater.from_dict(g["updater"])
    if isinstance(g.get("bias_updater"), dict):
        g["bias_updater"] = Updater.from_dict(g["bias_updater"])
    if isinstance(g.get("distribution"), dict):
        g["distribution"] = Distribution.from_dict(g["distribution"])
    if isinstance(g.get("dropout"), dict):
        g["dropout"] = IDropout.from_dict(g["dropout"])
    if isinstance(g.get("weight_noise"), dict):
        g["weight_noise"] = IWeightNoise.from_dict(g["weight_noise"])
    from deeplearning4j_tpu.nn.layers.base import activation_from_config
    g["activation"] = activation_from_config(g.get("activation"))
    for key in ("all_constraints", "weight_constraints", "bias_constraints"):
        if g.get(key):
            g[key] = [LayerConstraint.from_dict(c) if isinstance(c, dict)
                      else c for c in g[key]]
    return GlobalConf(**g)


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.builder()`` (DL4J ``new
    NeuralNetConfiguration.Builder()``)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConf()

    # fluent setters (DL4J Builder method names, snake_cased) ---------------
    def seed(self, s: int) -> "Builder":
        self._g.seed = int(s)
        return self

    def activation(self, a: str) -> "Builder":
        self._g.activation = a
        return self

    def weight_init(self, w: str, distribution: Optional[Distribution] = None) -> "Builder":
        self._g.weight_init = w
        if distribution is not None:
            self._g.distribution = distribution
        return self

    def dist(self, d: Distribution) -> "Builder":
        self._g.distribution = d
        self._g.weight_init = "distribution"
        return self

    def bias_init(self, b: float) -> "Builder":
        self._g.bias_init = b
        return self

    def updater(self, u: Union[str, Updater]) -> "Builder":
        self._g.updater = resolve_updater(u)
        return self

    def bias_updater(self, u: Union[str, Updater]) -> "Builder":
        self._g.bias_updater = resolve_updater(u)
        return self

    def l1(self, v: float) -> "Builder":
        self._g.l1 = v
        return self

    def l2(self, v: float) -> "Builder":
        self._g.l2 = v
        return self

    def l1_bias(self, v: float) -> "Builder":
        self._g.l1_bias = v
        return self

    def l2_bias(self, v: float) -> "Builder":
        self._g.l2_bias = v
        return self

    def dropout(self, keep_prob) -> "Builder":
        """Float keep probability (DL4J shorthand) or an IDropout instance
        (AlphaDropout, GaussianDropout, GaussianNoise, SpatialDropout)."""
        self._g.dropout = keep_prob
        return self

    def weight_noise(self, wn) -> "Builder":
        """IWeightNoise applied to every layer's weights at train forward
        time (``NeuralNetConfiguration.Builder.weightNoise:945``) — e.g.
        ``DropConnect(0.5)`` or ``WeightNoise(Distribution(...))``."""
        self._g.weight_noise = wn
        return self

    def constrain_all_parameters(self, *constraints) -> "Builder":
        """Apply constraints to ALL parameters of every layer after each
        update (``NeuralNetConfiguration.java:1031``)."""
        self._g.all_constraints = (self._g.all_constraints or []) + list(constraints)
        return self

    def constrain_bias(self, *constraints) -> "Builder":
        """Post-update constraints on bias parameters only (``:1043``)."""
        self._g.bias_constraints = (self._g.bias_constraints or []) + list(constraints)
        return self

    def constrain_weights(self, *constraints) -> "Builder":
        """Post-update constraints on weight parameters only (``:1055``)."""
        self._g.weight_constraints = (self._g.weight_constraints or []) + list(constraints)
        return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0) -> "Builder":
        self._g.gradient_normalization = mode
        self._g.gradient_normalization_threshold = threshold
        return self

    def dtype(self, dt: str) -> "Builder":
        self._g.dtype = dt
        return self

    def compute_dtype(self, dt: Optional[str]) -> "Builder":
        """Mixed precision: cast forward/backward compute to ``dt`` while
        params and updater state stay in ``dtype`` (master weights)."""
        self._g.compute_dtype = dt
        return self

    def gradient_checkpointing(self, enabled: bool = True) -> "Builder":
        """Rematerialize layer activations in the backward pass
        (jax.checkpoint) — less HBM for deep networks, ~1 extra forward of
        compute."""
        self._g.gradient_checkpointing = enabled
        return self

    def mini_batch(self, b: bool) -> "Builder":
        self._g.mini_batch = b
        return self

    def optimization_algo(self, algo: str) -> "Builder":
        self._g.optimization_algo = algo
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)

    def graph_builder(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
        return GraphBuilder(self._g)


class ListBuilder:
    """DL4J ``NeuralNetConfiguration.ListBuilder``."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type: str = "standard"
        self._tbptt_fwd: int = 20
        self._tbptt_bwd: int = 20
        self._input_pre_processors: dict = {}

    def input_pre_processor(self, index: int, spec: str) -> "ListBuilder":
        """Explicit preprocessor before layer ``index`` (DL4J
        ``ListBuilder.inputPreProcessor``), overriding automatic InputType
        inference. ``spec`` is a ``nn/conf/preprocessors.py`` spec string
        (e.g. ``"cnn_to_ff"``, ``"ff_to_cnn:28,28,1"``,
        ``"zero_mean|unit_variance"``)."""
        self._input_pre_processors[int(index)] = spec
        return self

    def layer(self, layer: Layer, index: Optional[int] = None) -> "ListBuilder":
        if index is not None and index != len(self._layers):
            raise ValueError("layers must be added in order")
        self._layers.append(layer)
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._input_type = input_type
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        self._backprop_type = normalize_backprop_type(t)
        return self

    def t_bptt_length(self, fwd: int, bwd: Optional[int] = None) -> "ListBuilder":
        self._tbptt_fwd = fwd
        self._tbptt_bwd = bwd if bwd is not None else fwd
        self._backprop_type = "truncated_bptt"
        return self

    def build(self) -> "MultiLayerConfiguration":
        conf = MultiLayerConfiguration(
            global_conf=self._g,
            layers=list(self._layers),
            input_type=self._input_type,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            input_pre_processors=dict(self._input_pre_processors),
        )
        conf.finalize()
        return conf


def normalize_backprop_type(t: str) -> str:
    """One spelling for every entry point (builder, from_dict, direct
    assignment): DL4J's ``BackpropType.TruncatedBPTT`` and shorthands all
    mean the truncated dispatch. Unknown spellings raise — a silently
    unrecognized value would train with the wrong regime."""
    t = (t or "standard").lower()
    if t in ("tbptt", "truncatedbptt", "truncated_bptt"):
        return "truncated_bptt"
    if t != "standard":
        raise ValueError(
            f"unknown backprop_type {t!r}; expected 'standard' or "
            f"'truncated_bptt' (aliases: TBPTT, TruncatedBPTT)")
    return t


@dataclasses.dataclass
class MultiLayerConfiguration:
    global_conf: GlobalConf
    layers: List[Layer]
    input_type: Optional[InputType] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    # explicit per-index preprocessor specs (ListBuilder.inputPreProcessor)
    input_pre_processors: dict = dataclasses.field(default_factory=dict)
    # computed in finalize():
    preprocessors: dict = dataclasses.field(default_factory=dict)  # idx -> fn
    layer_input_types: List[InputType] = dataclasses.field(default_factory=list)
    _finalized: bool = False

    def __post_init__(self):
        self.backprop_type = normalize_backprop_type(self.backprop_type)

    def finalize(self) -> None:
        """Propagate global defaults and infer shapes (DL4J's config build +
        InputType propagation)."""
        if self._finalized:
            return
        if not self.layers:
            raise ValueError("Configuration has no layers")
        for l in self.layers:
            l.apply_global_defaults(self.global_conf)  # type: ignore[arg-type]
        from deeplearning4j_tpu.nn.conf import preprocessors as pp
        it = self.input_type
        self.layer_input_types = []
        for i, l in enumerate(self.layers):
            if i in self.input_pre_processors:
                # explicit spec overrides automatic inference
                spec = self.input_pre_processors[i]
                self.preprocessors[i] = (lambda x, _s=spec: pp.apply(_s, x))
                if it is not None:
                    it = pp.output_type(spec, it)
            elif it is not None:
                pre = l.input_preprocessor(it)
                if pre is not None:
                    fn, it = pre
                    self.preprocessors[i] = fn
            if it is not None:
                l.set_n_in(it)
                self.layer_input_types.append(it)
                it = l.output_type(it)
            else:
                self.layer_input_types.append(None)  # type: ignore[arg-type]
        self._finalized = True

    # -- introspection -------------------------------------------------------
    def output_type(self) -> Optional[InputType]:
        if self.input_type is None:
            return None
        it = self.layer_input_types[-1]
        return self.layers[-1].output_type(it)

    def num_params(self) -> int:
        return sum(l.num_params() for l in self.layers)

    def memory_report(self, batch: int = 1) -> dict:
        """Analytic per-layer memory forecast (NetworkMemoryReport parity)."""
        import math
        report = {"layers": [], "total_param_bytes": 0, "total_activation_bytes": 0}
        bytes_per = 4 if self.global_conf.dtype == "float32" else 2
        it = self.input_type
        for i, l in enumerate(self.layers):
            n_params = l.num_params()
            act_elems = 0
            if it is not None:
                out = l.output_type(self.layer_input_types[i])
                act_elems = int(math.prod(out.batch_shape(batch)))
                it = out
            entry = {
                "name": l.name or f"layer{i}",
                "type": type(l).__name__,
                "params": n_params,
                "param_bytes": n_params * bytes_per,
                "activation_bytes": act_elems * bytes_per,
            }
            report["layers"].append(entry)
            report["total_param_bytes"] += entry["param_bytes"]
            report["total_activation_bytes"] += entry["activation_bytes"]
        return report

    # -- serde ---------------------------------------------------------------
    def to_dict(self) -> dict:
        g = global_conf_to_dict(self.global_conf)
        return {
            "format": "deeplearning4j_tpu.MultiLayerConfiguration",
            "version": 1,
            "global": g,
            "layers": [l.to_dict() for l in self.layers],
            "input_type": None if self.input_type is None else self.input_type.to_dict(),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_bwd_length": self.tbptt_bwd_length,
            "input_pre_processors": {str(k): v for k, v
                                     in self.input_pre_processors.items()},
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        conf = MultiLayerConfiguration(
            global_conf=global_conf_from_dict(d["global"]),
            layers=[layer_from_dict(ld) for ld in d["layers"]],
            input_type=None if d.get("input_type") is None else InputType.from_dict(d["input_type"]),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_bwd_length=d.get("tbptt_bwd_length", 20),
            input_pre_processors={int(k): v for k, v in
                                  d.get("input_pre_processors", {}).items()},
        )
        conf.finalize()
        return conf

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self, **kw) -> str:
        """YAML form of the same serde dict (``MultiLayerConfiguration
        .toYaml`` — the reference's Jackson YAML face)."""
        import yaml
        return yaml.safe_dump(json.loads(self.to_json()), sort_keys=False,
                              **kw)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))
