from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.network import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (  # noqa: F401
    ComputationGraphConfiguration,
    GraphBuilder,
)
from deeplearning4j_tpu.nn.conf.memory import (  # noqa: F401
    LayerMemoryReport,
    MemoryType,
    MemoryUseMode,
    NetworkMemoryReport,
    compiled_memory_analysis,
    network_memory_report,
)
