"""Named input preprocessors (DL4J ``InputPreProcessor`` family).

Reference: ``nn/conf/preprocessor/`` — CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor,
ZeroMeanPrePreProcessor, UnitVarianceProcessor,
ZeroMeanAndUnitVariancePreProcessor, BinomialSamplingPreProcessor,
ComposableInputPreProcessor — plus the Keras-import
TensorFlowCnnToFeedForwardPreProcessor.

Each preprocessor is addressed by a spec string so graph configs stay
JSON-serializable: ``"cnn_to_ff"``, parameterized ``"ff_to_cnn:28,28,1"``,
or composed with ``|`` (``"zero_mean|unit_variance"`` =
ComposableInputPreProcessor). Data layout here is NHWC / [N,T,C]
(channels-last), so most conversions are pure reshapes XLA folds away.
Backward shape mapping (the reference's ``backprop`` half) comes free
from autodiff. Explicit placement between layers:
``ListBuilder.input_pre_processor(idx, spec)``, overriding the automatic
InputType inference like ``NeuralNetConfiguration.ListBuilder
.inputPreProcessor`` does.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType


def _parse(spec: str) -> Tuple[str, Tuple[int, ...]]:
    if ":" in spec:
        name, args = spec.split(":", 1)
        return name, tuple(int(a) for a in args.split(","))
    return spec, ()


def _safe_std(x):
    """Per-column std with 0-variance columns mapped to 1 — masked BEFORE
    the sqrt so the backward pass stays finite (the naive
    ``where(std==0, 1, std)`` still differentiates sqrt at 0 -> NaN)."""
    var = jnp.var(x, axis=0, keepdims=True)
    zero = var == 0
    return jnp.where(zero, 1.0, jnp.sqrt(jnp.where(zero, 1.0, var)))


def apply(spec: str, x):
    if "|" in spec:  # ComposableInputPreProcessor
        for part in spec.split("|"):
            x = apply(part, x)
        return x
    name, args = _parse(spec)
    if name == "identity":
        return x
    # zero_mean/unit_variance/standardize use PER-FEATURE statistics over
    # the batch axis (column means/stds), matching the reference's
    # subiRowVector(mean(0)) / diviRowVector(std(0)) semantics
    if name == "zero_mean":          # ZeroMeanPrePreProcessor
        return x - jnp.mean(x, axis=0, keepdims=True)
    if name == "unit_variance":      # UnitVarianceProcessor
        return x / _safe_std(x)
    if name == "standardize":        # ZeroMeanAndUnitVariancePreProcessor
        mean = jnp.mean(x, axis=0, keepdims=True)
        return (x - mean) / _safe_std(x)
    if name == "binomial_sampling":  # BinomialSamplingPreProcessor
        # stateless draw, deterministic per seed — one fixed mask per
        # traced program (the reference's ND4J RNG is stateful; under jit
        # the key must be data-independent). Straight-through gradient:
        # the reference's backprop passes epsilons through unchanged, and
        # a raw bernoulli would zero every upstream gradient.
        seed = args[0] if args else 0
        key = jax.random.PRNGKey(seed)
        sample = jax.random.bernoulli(key, jnp.clip(x, 0.0, 1.0)).astype(x.dtype)
        return x + jax.lax.stop_gradient(sample - x)
    if name == "cnn_to_ff":          # [N,H,W,C] → [N, H*W*C]
        return x.reshape(x.shape[0], -1)
    if name == "ff_to_cnn":          # [N, H*W*C] → [N,H,W,C]
        h, w, c = args
        return x.reshape(x.shape[0], h, w, c)
    if name == "rnn_to_ff":          # [N,T,C] → [N*T, C]
        return x.reshape(-1, x.shape[-1])
    if name == "ff_to_rnn":          # [N*T, C] → [N,T,C]
        (t,) = args
        return x.reshape(-1, t, x.shape[-1])
    if name == "cnn_to_rnn":         # [N,H,W,C] → [N, T=H*W, C]... DL4J: [N, H*W*C] per step? No:
        # DL4J CnnToRnnPreProcessor: [N,C,H,W] per timestep flattened → here
        # [N,H,W,C] → [N, 1, H*W*C] is not the semantic; the reference input
        # is [N*T,...]. We treat the H axis as time: [N, H, W*C].
        return x.reshape(x.shape[0], x.shape[1], -1)
    if name == "rnn_to_cnn":         # [N,T,C] with C=H'*W'*C' → [N,H',W',C'] per step merged
        h, w, c = args
        return x.reshape(-1, h, w, c)
    if name == "reshape":            # ReshapePreprocessor (Keras Reshape):
        # raw row-major reshape of everything after the batch axis
        return x.reshape((x.shape[0],) + args)
    raise ValueError(f"unknown preprocessor {spec!r}")


def output_type(spec: str, it: InputType) -> InputType:
    if "|" in spec:
        for part in spec.split("|"):
            it = output_type(part, it)
        return it
    name, args = _parse(spec)
    if name in ("identity", "zero_mean", "unit_variance", "standardize",
                "binomial_sampling"):
        return it
    if name == "cnn_to_ff":
        return InputType.feed_forward(it.height * it.width * it.channels)
    if name == "ff_to_cnn":
        h, w, c = args
        return InputType.convolutional(h, w, c)
    if name == "rnn_to_ff":
        return InputType.feed_forward(it.size)
    if name == "ff_to_rnn":
        (t,) = args
        return InputType.recurrent(it.size, t)
    if name == "cnn_to_rnn":
        return InputType.recurrent(it.width * it.channels, it.height)
    if name == "rnn_to_cnn":
        h, w, c = args
        return InputType.convolutional(h, w, c)
    if name == "reshape":
        # target rank decides the interpretation (channels-last, like the
        # rest of the framework): 1→ff, 2→[T,C] recurrent, 3→[H,W,C] conv,
        # 4→[T,H,W,C] image sequence
        if len(args) == 1:
            return InputType.feed_forward(args[0])
        if len(args) == 2:
            return InputType.recurrent(args[1], args[0])
        if len(args) == 3:
            return InputType.convolutional(*args)
        if len(args) == 4:
            t, h, w, c = args
            return InputType.recurrent_convolutional(h, w, c, t)
        raise ValueError(f"reshape target rank {len(args)} unsupported")
    raise ValueError(f"unknown preprocessor {spec!r}")
