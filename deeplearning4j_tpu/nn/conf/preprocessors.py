"""Named input preprocessors (DL4J ``InputPreProcessor`` family).

Reference: ``nn/conf/preprocessor/`` — CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor —
plus the Keras-import TensorFlowCnnToFeedForwardPreProcessor.

Each preprocessor is addressed by a spec string so graph configs stay
JSON-serializable: ``"cnn_to_ff"`` or parameterized ``"ff_to_cnn:28,28,1"``.
Data layout here is NHWC / [N,T,C] (channels-last), so most conversions are
pure reshapes XLA folds away.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType


def _parse(spec: str) -> Tuple[str, Tuple[int, ...]]:
    if ":" in spec:
        name, args = spec.split(":", 1)
        return name, tuple(int(a) for a in args.split(","))
    return spec, ()


def apply(spec: str, x):
    name, args = _parse(spec)
    if name == "identity":
        return x
    if name == "cnn_to_ff":          # [N,H,W,C] → [N, H*W*C]
        return x.reshape(x.shape[0], -1)
    if name == "ff_to_cnn":          # [N, H*W*C] → [N,H,W,C]
        h, w, c = args
        return x.reshape(x.shape[0], h, w, c)
    if name == "rnn_to_ff":          # [N,T,C] → [N*T, C]
        return x.reshape(-1, x.shape[-1])
    if name == "ff_to_rnn":          # [N*T, C] → [N,T,C]
        (t,) = args
        return x.reshape(-1, t, x.shape[-1])
    if name == "cnn_to_rnn":         # [N,H,W,C] → [N, T=H*W, C]... DL4J: [N, H*W*C] per step? No:
        # DL4J CnnToRnnPreProcessor: [N,C,H,W] per timestep flattened → here
        # [N,H,W,C] → [N, 1, H*W*C] is not the semantic; the reference input
        # is [N*T,...]. We treat the H axis as time: [N, H, W*C].
        return x.reshape(x.shape[0], x.shape[1], -1)
    if name == "rnn_to_cnn":         # [N,T,C] with C=H'*W'*C' → [N,H',W',C'] per step merged
        h, w, c = args
        return x.reshape(-1, h, w, c)
    raise ValueError(f"unknown preprocessor {spec!r}")


def output_type(spec: str, it: InputType) -> InputType:
    name, args = _parse(spec)
    if name == "identity":
        return it
    if name == "cnn_to_ff":
        return InputType.feed_forward(it.height * it.width * it.channels)
    if name == "ff_to_cnn":
        h, w, c = args
        return InputType.convolutional(h, w, c)
    if name == "rnn_to_ff":
        return InputType.feed_forward(it.size)
    if name == "ff_to_rnn":
        (t,) = args
        return InputType.recurrent(it.size, t)
    if name == "cnn_to_rnn":
        return InputType.recurrent(it.width * it.channels, it.height)
    if name == "rnn_to_cnn":
        h, w, c = args
        return InputType.convolutional(h, w, c)
    raise ValueError(f"unknown preprocessor {spec!r}")
