"""Input type inference — the DL4J ``InputType`` system.

Reference: ``nn/conf/inputs/InputType.java`` (kinds FF / RNN / CNN / CNNFlat).
Shape convention is TPU-first: convolutional activations are **NHWC**
(channels-last) so XLA lowers convs straight onto the MXU without layout
transposes; DL4J's NCHW is converted at the import boundary only.

Recurrent activations are **[batch, time, size]** (time-major inside
``lax.scan`` is handled by the layer impls), vs DL4J's [batch, size, time].
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnn_flat" | "cnn3d" | "cnn1d" | "cnn_seq"
    size: int = 0                      # ff / rnn feature size
    timesteps: Optional[int] = None    # rnn (None = variable)
    height: int = 0
    width: int = 0
    channels: int = 0
    depth: int = 0                     # cnn3d

    # -- factories mirroring InputType.feedForward(...) etc. ----------------
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="rnn", size=int(size), timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn", height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn_flat", height=int(height), width=int(width),
                         channels=int(channels), size=int(height * width * channels))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn3d", depth=int(depth), height=int(height),
                         width=int(width), channels=int(channels))

    @staticmethod
    def recurrent_convolutional(height: int, width: int, channels: int,
                                timesteps: Optional[int] = None) -> "InputType":
        """A sequence of images [batch, time, H, W, C] (ConvLSTM2D data)."""
        return InputType(kind="cnn_seq", height=int(height), width=int(width),
                         channels=int(channels), timesteps=timesteps)

    @staticmethod
    def recurrent1d(size: int, timesteps: Optional[int] = None) -> "InputType":
        # Convolution1D operates on [batch, time, channels] == rnn layout
        return InputType.recurrent(size, timesteps)

    # -- helpers -----------------------------------------------------------
    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.size
        if self.kind == "rnn":
            return self.size
        if self.kind in ("cnn", "cnn_flat", "cnn_seq"):
            return self.height * self.width * self.channels
        if self.kind == "cnn3d":
            return self.depth * self.height * self.width * self.channels
        raise ValueError(self.kind)

    def batch_shape(self, batch: int = 1) -> Tuple[int, ...]:
        """Example array shape for one batch of this type (NHWC / NTC)."""
        if self.kind == "ff" or self.kind == "cnn_flat":
            return (batch, self.flat_size())
        if self.kind == "rnn":
            t = self.timesteps if self.timesteps is not None else 1
            return (batch, t, self.size)
        if self.kind == "cnn":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "cnn3d":
            return (batch, self.depth, self.height, self.width, self.channels)
        if self.kind == "cnn_seq":
            t = self.timesteps if self.timesteps is not None else 1
            return (batch, t, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def cnn_seq_to_rnn(self):
        """Per-step flatten preprocessor for image sequences: [N,T,H,W,C] →
        [N,T,H*W*C]. Shared by every layer that consumes flat sequence input
        after a ConvLSTM/TimeDistributed-conv stage."""
        assert self.kind == "cnn_seq", self.kind
        return (lambda x: x.reshape(x.shape[0], x.shape[1], -1),
                InputType.recurrent(self.flat_size(), self.timesteps))

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)
