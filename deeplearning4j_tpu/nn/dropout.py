"""Dropout variants — the ``IDropout`` SPI and its four reference impls.

Reference: ``nn/conf/dropout/`` — ``IDropout.java`` (SPI),
``Dropout.java`` (inverted dropout via ``DropOutInverted``),
``AlphaDropout.java:38`` (SNN dropout, Klambauer et al. 2017),
``GaussianDropout.java`` (multiplicative N(1, sqrt(rate/(1-rate)))),
``GaussianNoise.java`` (additive N(0, stddev)). ``SpatialDropout`` is the
Keras noise layer the importer needs (drops whole channels).

A layer's ``dropout`` field accepts a plain float (keep probability,
DL4J-style shorthand for :class:`Dropout`) or any :class:`IDropout`
instance. All impls are pure jnp functions of (x, rng) so they trace into
the jitted train step; at inference they are identity, matching the
reference's train-only application.

Dropout SCHEDULES (``pSchedule``) are not supported: the iteration counter
is not threaded into layer forward calls by design (it would fragment the
compiled step). Passing a Schedule raises.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

DROPOUT_REGISTRY: Dict[str, type] = {}


def register_dropout(cls):
    DROPOUT_REGISTRY[cls.__name__] = cls
    return cls


def _check_no_schedule(value, what: str):
    from deeplearning4j_tpu.nn.updaters import Schedule
    if isinstance(value, Schedule):
        raise ValueError(
            f"{what} schedules are not supported (the iteration counter is "
            "not threaded into layer forwards); use a fixed value")
    return float(value)


@dataclasses.dataclass
class IDropout:
    """SPI (``conf/dropout/IDropout.java``): train-time activation noise."""

    def apply(self, x: Array, rng: jax.Array, train: bool) -> Array:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {k: v for k, v in dataclasses.asdict(self).items() if v is not None}
        d["@dropout"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "IDropout":
        d = dict(d)
        cls = DROPOUT_REGISTRY[d.pop("@dropout")]
        return cls(**d)


@register_dropout
@dataclasses.dataclass
class Dropout(IDropout):
    """Inverted dropout (``Dropout.java``, via ``DropOutInverted``):
    keep with probability ``p``, scale kept values by ``1/p``."""

    p: float = 0.5

    def __post_init__(self):
        self.p = _check_no_schedule(self.p, "Dropout")
        if not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Activation retain probability must be in (0, 1]: got {self.p}")

    def apply(self, x, rng, train):
        if not train or self.p >= 1.0 or rng is None:
            return x
        keep = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(keep, x / self.p, jnp.zeros((), x.dtype))


@register_dropout
@dataclasses.dataclass
class AlphaDropout(IDropout):
    """Self-normalizing-network dropout (``AlphaDropout.java:38``,
    https://arxiv.org/abs/1706.02515 pg6): a·(x·d + α'·(1−d)) + b with
    d ~ Bernoulli(p), α' = −λα, and a, b chosen so mean AND variance of the
    activations are preserved. Pair with SELU activation + NORMAL init."""

    p: float = 0.5
    alpha: float = 1.6732632423543772   # DEFAULT_ALPHA
    lambda_: float = 1.0507009873554804  # DEFAULT_LAMBDA

    def __post_init__(self):
        self.p = _check_no_schedule(self.p, "AlphaDropout")
        if not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Activation retain probability must be in (0, 1]: got {self.p}")

    @property
    def alpha_prime(self) -> float:
        return -self.lambda_ * self.alpha

    def a(self, p: float) -> float:
        """``AlphaDropout.java:123``: 1/sqrt(p + α'²·p·(1−p))."""
        ap = self.alpha_prime
        return 1.0 / math.sqrt(p + ap * ap * p * (1.0 - p))

    def b(self, p: float) -> float:
        """``AlphaDropout.java:127``: −a(p)·(1−p)·α'."""
        return -self.a(p) * (1.0 - p) * self.alpha_prime

    def apply(self, x, rng, train):
        if not train or self.p >= 1.0 or rng is None:
            return x
        d = jax.random.bernoulli(rng, self.p, x.shape)
        a = jnp.asarray(self.a(self.p), x.dtype)
        b = jnp.asarray(self.b(self.p), x.dtype)
        ap = jnp.asarray(self.alpha_prime, x.dtype)
        return a * jnp.where(d, x, ap) + b


@register_dropout
@dataclasses.dataclass
class GaussianDropout(IDropout):
    """Multiplicative Gaussian noise (``GaussianDropout.java``, Srivastava
    et al. 2014 §10): x · N(1, sqrt(rate/(1−rate)))."""

    rate: float = 0.5

    def __post_init__(self):
        self.rate = _check_no_schedule(self.rate, "GaussianDropout")
        if not (0.0 <= self.rate < 1.0):
            raise ValueError(f"rate must be in [0, 1): got {self.rate}")

    def apply(self, x, rng, train):
        if not train or self.rate == 0.0 or rng is None:
            return x
        stdev = math.sqrt(self.rate / (1.0 - self.rate))
        noise = 1.0 + stdev * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise


@register_dropout
@dataclasses.dataclass
class GaussianNoise(IDropout):
    """Additive zero-mean Gaussian noise (``GaussianNoise.java``):
    x + N(0, stddev)."""

    stddev: float = 0.1

    def __post_init__(self):
        self.stddev = _check_no_schedule(self.stddev, "GaussianNoise")

    def apply(self, x, rng, train):
        if not train or self.stddev == 0.0 or rng is None:
            return x
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


@register_dropout
@dataclasses.dataclass
class SpatialDropout(IDropout):
    """Channel dropout (Keras SpatialDropout1D/2D/3D; Tompson et al. 2015):
    drops entire feature maps — the Bernoulli mask covers only (batch,
    channels) and broadcasts over the spatial/time axes (channels-last).
    ``p`` is the KEEP probability with inverted scaling, like
    :class:`Dropout`."""

    p: float = 0.5

    def __post_init__(self):
        self.p = _check_no_schedule(self.p, "SpatialDropout")
        if not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Activation retain probability must be in (0, 1]: got {self.p}")

    def apply(self, x, rng, train):
        if not train or self.p >= 1.0 or rng is None:
            return x
        if x.ndim < 3:
            raise ValueError(
                f"SpatialDropout expects [N, ..., C] rank>=3 input, got shape "
                f"{x.shape}; use Dropout for 2d activations")
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, self.p, mask_shape)
        return jnp.where(keep, x / self.p, jnp.zeros((), x.dtype))


def resolve_dropout(v) -> Optional[IDropout]:
    """Normalize a layer's ``dropout`` config value: float keep-prob →
    :class:`Dropout`; IDropout instances pass through; None stays None.
    Keep-prob <= 0 or >= 1 floats mean "off" (DL4J treats them as no-op)."""
    if v is None or isinstance(v, IDropout):
        return v
    p = float(v)
    if p <= 0.0 or p >= 1.0:
        return None
    return Dropout(p)
