"""Dropout variants — the ``IDropout`` SPI and its four reference impls.

Reference: ``nn/conf/dropout/`` — ``IDropout.java`` (SPI),
``Dropout.java`` (inverted dropout via ``DropOutInverted``),
``AlphaDropout.java:38`` (SNN dropout, Klambauer et al. 2017),
``GaussianDropout.java`` (multiplicative N(1, sqrt(rate/(1-rate)))),
``GaussianNoise.java`` (additive N(0, stddev)). ``SpatialDropout`` is the
Keras noise layer the importer needs (drops whole channels).

A layer's ``dropout`` field accepts a plain float (keep probability,
DL4J-style shorthand for :class:`Dropout`) or any :class:`IDropout`
instance. All impls are pure jnp functions of (x, rng) so they trace into
the jitted train step; at inference they are identity, matching the
reference's train-only application.

Dropout SCHEDULES (``Dropout.java:45,68`` ``pSchedule``, and the
``rateSchedule``/``stddevSchedule`` twins on the Gaussian variants) are
supported: any scalar field also accepts a ``Schedule``, evaluated at the
device-resident ``(iteration, epoch)`` tick the train step carries
(``nn/tick.py``) — the schedule compiles INTO the step as a function of
the tick tracers, so no retrace or step fragmentation occurs. Outside a
train step (probe forwards) a schedule evaluates at tick (0, 0).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

DROPOUT_REGISTRY: Dict[str, type] = {}


def register_dropout(cls):
    DROPOUT_REGISTRY[cls.__name__] = cls
    return cls


def _coerce_scalar(value):
    """Config-time normalization: Schedules (and their serde dicts) pass
    through; everything else becomes a float."""
    from deeplearning4j_tpu.nn.updaters import Schedule
    if isinstance(value, Schedule):
        return value
    if isinstance(value, dict) and "@schedule" in value:
        return Schedule.from_dict(value)
    return float(value)


def _now(value, lo=None, hi=None):
    """Apply-time value: floats as-is; Schedules evaluated at the train
    step's device tick (a tracer inside jit — the schedule fuses into the
    compiled step). ``lo``/``hi`` clamp SCHEDULED values into the field's
    valid range — a schedule that wanders out of range (e.g. a decay
    driving retain-p to 0) cannot be rejected loudly inside jit the way a
    bad fixed float is at construction, so it saturates instead of
    producing division-by-zero NaNs."""
    from deeplearning4j_tpu.nn.updaters import Schedule
    if isinstance(value, Schedule):
        from deeplearning4j_tpu.nn.tick import current_schedule_tick
        v = value.value(*current_schedule_tick())
        if lo is not None or hi is not None:
            v = jnp.clip(v, lo, hi)
        return v
    return value


def _is_schedule(value) -> bool:
    from deeplearning4j_tpu.nn.updaters import Schedule
    return isinstance(value, Schedule)


@dataclasses.dataclass
class IDropout:
    """SPI (``conf/dropout/IDropout.java``): train-time activation noise."""

    def apply(self, x: Array, rng: jax.Array, train: bool) -> Array:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            d[f.name] = v.to_dict() if _is_schedule(v) else v
        d["@dropout"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "IDropout":
        d = dict(d)
        cls = DROPOUT_REGISTRY[d.pop("@dropout")]
        return cls(**d)  # scalar fields re-inflate schedules via _coerce_scalar


@register_dropout
@dataclasses.dataclass
class Dropout(IDropout):
    """Inverted dropout (``Dropout.java``, via ``DropOutInverted``):
    keep with probability ``p``, scale kept values by ``1/p``. ``p`` may
    be a ``Schedule`` (``Dropout.java:45`` ``pSchedule`` on the retain
    probability), evaluated at the step's device tick."""

    p: float = 0.5

    def __post_init__(self):
        self.p = _coerce_scalar(self.p)
        if not _is_schedule(self.p) and not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Activation retain probability must be in (0, 1]: got {self.p}")

    def apply(self, x, rng, train):
        if not train or rng is None:
            return x
        if not _is_schedule(self.p) and self.p >= 1.0:
            return x
        p = _now(self.p, lo=1e-6, hi=1.0)
        keep = jax.random.bernoulli(rng, p, x.shape)
        return jnp.where(keep, x / jnp.asarray(p, x.dtype),
                         jnp.zeros((), x.dtype))


@register_dropout
@dataclasses.dataclass
class AlphaDropout(IDropout):
    """Self-normalizing-network dropout (``AlphaDropout.java:38``,
    https://arxiv.org/abs/1706.02515 pg6): a·(x·d + α'·(1−d)) + b with
    d ~ Bernoulli(p), α' = −λα, and a, b chosen so mean AND variance of the
    activations are preserved. Pair with SELU activation + NORMAL init."""

    p: float = 0.5
    alpha: float = 1.6732632423543772   # DEFAULT_ALPHA
    lambda_: float = 1.0507009873554804  # DEFAULT_LAMBDA

    def __post_init__(self):
        self.p = _coerce_scalar(self.p)
        if not _is_schedule(self.p) and not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Activation retain probability must be in (0, 1]: got {self.p}")

    @property
    def alpha_prime(self) -> float:
        return -self.lambda_ * self.alpha

    def a(self, p: float) -> float:
        """``AlphaDropout.java:123``: 1/sqrt(p + α'²·p·(1−p))."""
        ap = self.alpha_prime
        return 1.0 / math.sqrt(p + ap * ap * p * (1.0 - p))

    def b(self, p: float) -> float:
        """``AlphaDropout.java:127``: −a(p)·(1−p)·α'."""
        return -self.a(p) * (1.0 - p) * self.alpha_prime

    def apply(self, x, rng, train):
        if not train or rng is None:
            return x
        if not _is_schedule(self.p) and self.p >= 1.0:
            return x
        p = _now(self.p, lo=1e-6, hi=1.0)
        ap = self.alpha_prime
        # jnp forms of a(p)/b(p) so a scheduled p (a tracer) flows through
        a = 1.0 / jnp.sqrt(p + ap * ap * p * (1.0 - p))
        b = -a * (1.0 - p) * ap
        d = jax.random.bernoulli(rng, p, x.shape)
        return (jnp.asarray(a, x.dtype) * jnp.where(d, x, jnp.asarray(ap, x.dtype))
                + jnp.asarray(b, x.dtype))


@register_dropout
@dataclasses.dataclass
class GaussianDropout(IDropout):
    """Multiplicative Gaussian noise (``GaussianDropout.java``, Srivastava
    et al. 2014 §10): x · N(1, sqrt(rate/(1−rate)))."""

    rate: float = 0.5

    def __post_init__(self):
        self.rate = _coerce_scalar(self.rate)
        if not _is_schedule(self.rate) and not (0.0 <= self.rate < 1.0):
            raise ValueError(f"rate must be in [0, 1): got {self.rate}")

    def apply(self, x, rng, train):
        if not train or rng is None:
            return x
        if not _is_schedule(self.rate) and self.rate == 0.0:
            return x
        rate = _now(self.rate, lo=0.0, hi=1.0 - 1e-6)
        stdev = jnp.sqrt(rate / (1.0 - rate))
        noise = 1.0 + jnp.asarray(stdev, x.dtype) * jax.random.normal(
            rng, x.shape, x.dtype)
        return x * noise


@register_dropout
@dataclasses.dataclass
class GaussianNoise(IDropout):
    """Additive zero-mean Gaussian noise (``GaussianNoise.java``):
    x + N(0, stddev)."""

    stddev: float = 0.1

    def __post_init__(self):
        self.stddev = _coerce_scalar(self.stddev)

    def apply(self, x, rng, train):
        if not train or rng is None:
            return x
        if not _is_schedule(self.stddev) and self.stddev == 0.0:
            return x
        return x + jnp.asarray(_now(self.stddev, lo=0.0), x.dtype) * jax.random.normal(
            rng, x.shape, x.dtype)


@register_dropout
@dataclasses.dataclass
class SpatialDropout(IDropout):
    """Channel dropout (Keras SpatialDropout1D/2D/3D; Tompson et al. 2015):
    drops entire feature maps — the Bernoulli mask covers only (batch,
    channels) and broadcasts over the spatial/time axes (channels-last).
    ``p`` is the KEEP probability with inverted scaling, like
    :class:`Dropout`."""

    p: float = 0.5

    def __post_init__(self):
        self.p = _coerce_scalar(self.p)
        if not _is_schedule(self.p) and not (0.0 < self.p <= 1.0):
            raise ValueError(
                f"Activation retain probability must be in (0, 1]: got {self.p}")

    def apply(self, x, rng, train):
        if not train or rng is None:
            return x
        if not _is_schedule(self.p) and self.p >= 1.0:
            return x
        if x.ndim < 3:
            raise ValueError(
                f"SpatialDropout expects [N, ..., C] rank>=3 input, got shape "
                f"{x.shape}; use Dropout for 2d activations")
        p = _now(self.p, lo=1e-6, hi=1.0)
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, p, mask_shape)
        return jnp.where(keep, x / jnp.asarray(p, x.dtype),
                         jnp.zeros((), x.dtype))


def resolve_dropout(v) -> Optional[IDropout]:
    """Normalize a layer's ``dropout`` config value: float keep-prob →
    :class:`Dropout`; a ``Schedule`` → :class:`Dropout` on that schedule
    (DL4J's ``Dropout(ISchedule)`` constructor); IDropout instances pass
    through; None stays None. Keep-prob <= 0 or >= 1 floats mean "off"
    (DL4J treats them as no-op)."""
    if v is None or isinstance(v, IDropout):
        return v
    if _is_schedule(v):
        return Dropout(v)
    p = float(v)
    if p <= 0.0 or p >= 1.0:
        return None
    return Dropout(p)
