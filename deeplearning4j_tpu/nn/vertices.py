"""Graph vertices — the non-layer nodes of a ComputationGraph DAG.

Reference: config classes in ``nn/conf/graph/`` paired with runtime impls in
``nn/graph/vertex/impl/`` (MergeVertex, ElementWiseVertex, StackVertex,
UnstackVertex, SubsetVertex, ReshapeVertex, ScaleVertex, ShiftVertex,
L2NormalizeVertex, L2Vertex, PoolHelperVertex, PreprocessorVertex, and the
rnn vertices LastTimeStepVertex / DuplicateToTimeSeriesVertex /
ReverseTimeSeriesVertex). Here each vertex is one dataclass with a pure
``forward(inputs)`` — backprop is ``jax.grad`` through it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

Array = jax.Array

VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class GraphVertex:
    """Parameterless DAG node: pure function of its input activations."""

    def output_type(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def forward(self, inputs: List[Array],
                masks: Optional[List[Optional[Array]]] = None) -> Array:
        raise NotImplementedError

    def output_mask(self, masks: List[Optional[Array]]) -> Optional[Array]:
        """Mask propagation; default: pass through the first input's mask."""
        for m in masks:
            if m is not None:
                return m
        return None

    # -- serde ---------------------------------------------------------------
    def to_dict(self) -> dict:
        d = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}
        d["@vertex"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@vertex")]
        for k, v in d.items():
            if isinstance(v, list):
                d[k] = tuple(v)
        return cls(**d)


@register_vertex
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis (channels for NHWC, features for
    FF/RNN — always the last axis here). Reference: MergeVertex.java."""

    def output_type(self, input_types: List[InputType]) -> InputType:
        t0 = input_types[0]
        if t0.kind == "cnn":
            return InputType.convolutional(
                t0.height, t0.width, sum(t.channels for t in input_types))
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in input_types), t0.timesteps)
        return InputType.feed_forward(sum(t.size for t in input_types))

    def forward(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=-1)


@register_vertex
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """add | average | subtract | product | max (ElementWiseVertex.java)."""

    op: str = "add"

    def forward(self, inputs, masks=None):
        op = self.op.lower()
        if op in ("add", "sum"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op in ("average", "avg"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out / len(inputs)
        if op in ("subtract", "sub"):
            if len(inputs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            return inputs[0] - inputs[1]
        if op in ("product", "mul"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"unknown op {self.op!r}")


@register_vertex
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Concatenate along the batch (first) axis (StackVertex.java)."""

    def output_type(self, input_types):
        return input_types[0]

    def forward(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Slice index ``from_index`` of ``stack_size`` equal batch chunks
    (UnstackVertex.java)."""

    from_index: int = 0
    stack_size: int = 1

    def forward(self, inputs, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step]


@register_vertex
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature range [from_index, to_index] inclusive (SubsetVertex.java)."""

    from_index: int = 0
    to_index: int = 0

    def output_type(self, input_types):
        n = self.to_index - self.from_index + 1
        t0 = input_types[0]
        if t0.kind == "rnn":
            return InputType.recurrent(n, t0.timesteps)
        return InputType.feed_forward(n)

    def forward(self, inputs, masks=None):
        return inputs[0][..., self.from_index:self.to_index + 1]


@register_vertex
@dataclasses.dataclass
class ReshapeVertex(GraphVertex):
    """Reshape to ``shape`` (batch dim preserved as -1). ReshapeVertex.java."""

    shape: Tuple[int, ...] = ()

    def output_type(self, input_types):
        s = tuple(self.shape)
        if len(s) == 1:
            return InputType.feed_forward(s[0])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        return input_types[0]

    def forward(self, inputs, masks=None):
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape))


@register_vertex
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    """Multiply by a fixed scalar (ScaleVertex.java)."""

    scale_factor: float = 1.0

    def forward(self, inputs, masks=None):
        return inputs[0] * self.scale_factor


@register_vertex
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    """Add a fixed scalar (ShiftVertex.java)."""

    shift_factor: float = 0.0

    def forward(self, inputs, masks=None):
        return inputs[0] + self.shift_factor


@register_vertex
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over non-batch dims (L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def forward(self, inputs, masks=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / (norm + self.eps)


@register_vertex
@dataclasses.dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance of two inputs → [N, 1] (L2Vertex.java)."""

    eps: float = 1e-8

    def output_type(self, input_types):
        return InputType.feed_forward(1)

    def forward(self, inputs, masks=None):
        a, b = inputs
        d = (a - b).reshape(a.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps)


@register_vertex
@dataclasses.dataclass
class PoolHelperVertex(GraphVertex):
    """Strip the first row+column of an NHWC map — compatibility shim for
    imported GoogLeNet-style models (PoolHelperVertex.java)."""

    def output_type(self, input_types):
        t = input_types[0]
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)

    def forward(self, inputs, masks=None):
        return inputs[0][:, 1:, 1:, :]


@register_vertex
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """[N,T,C] → [N,C] at the last unmasked step (rnn/LastTimeStepVertex.java).
    ``mask_input`` names the network input whose mask applies."""

    mask_input: Optional[str] = None

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)

    def forward(self, inputs, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return x[:, -1, :]
        idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
        return x[jnp.arange(x.shape[0]), idx]

    def output_mask(self, masks):
        return None  # time dimension collapsed


@register_vertex
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[N,C] → [N,T,C], T taken from a reference time-series input
    (rnn/DuplicateToTimeSeriesVertex.java)."""

    ts_input: Optional[str] = None

    def output_type(self, input_types):
        # second input (or the named ts input) provides T
        t = input_types[1].timesteps if len(input_types) > 1 else None
        return InputType.recurrent(input_types[0].size, t)

    def forward(self, inputs, masks=None):
        x, ref = inputs[0], inputs[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], ref.shape[1], x.shape[-1]))

    def output_mask(self, masks):
        return masks[1] if len(masks) > 1 else None


@register_vertex
@dataclasses.dataclass
class ReverseTimeSeriesVertex(GraphVertex):
    """Reverse along time, respecting masks (rnn/ReverseTimeSeriesVertex.java)."""

    mask_input: Optional[str] = None

    def forward(self, inputs, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return jnp.flip(x, axis=1)
        # reverse only the valid prefix of each sequence
        lengths = jnp.sum(mask, axis=1).astype(jnp.int32)  # [N]
        t = x.shape[1]
        pos = jnp.arange(t)[None, :]
        src = jnp.where(pos < lengths[:, None], lengths[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(x, src[:, :, None], axis=1)


@register_vertex
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Wraps a named input preprocessor (PreprocessorVertex.java). The
    preprocessor is identified by name for serializability; see
    ``deeplearning4j_tpu.nn.conf.preprocessors``."""

    preprocessor: str = "identity"

    def output_type(self, input_types):
        from deeplearning4j_tpu.nn.conf.preprocessors import output_type as pp_out
        return pp_out(self.preprocessor, input_types[0])

    def forward(self, inputs, masks=None):
        from deeplearning4j_tpu.nn.conf.preprocessors import apply as pp_apply
        return pp_apply(self.preprocessor, inputs[0])
