"""Activation functions with DL4J ``Activation`` enum parity.

Reference: the ND4J ``IActivation`` implementations used throughout
deeplearning4j-nn (e.g. layer configs take ``Activation`` values —
``nn/conf/layers/*.java``). Each activation here is a pure jnp function so XLA
fuses it into the surrounding matmul/conv; there are no hand-written
derivative pairs — ``jax.grad`` differentiates through them.

All functions take and return a single array. Parametric activations
(leakyrelu alpha, elu alpha, …) are exposed through ``resolve`` which accepts
either a name or a (name, kwargs) tuple and returns a closed-over callable.
"""

from __future__ import annotations

import math
from typing import Callable, Union

import jax
import jax.numpy as jnp

Array = jax.Array
ActivationFn = Callable[[Array], Array]

_SELU_ALPHA = 1.6732632423543772
_SELU_LAMBDA = 1.0507009873554805


def identity(x: Array) -> Array:
    return x


def relu(x: Array) -> Array:
    return jnp.maximum(x, 0)


def relu6(x: Array) -> Array:
    return jnp.clip(x, 0, 6)


def leakyrelu(x: Array, alpha: float = 0.01) -> Array:
    return jnp.where(x >= 0, x, alpha * x)


def elu(x: Array, alpha: float = 1.0) -> Array:
    safe = jnp.where(x > 0, 0.0, x)  # keep exp() off the positive branch
    return jnp.where(x > 0, x, alpha * (jnp.exp(safe) - 1.0))


def selu(x: Array) -> Array:
    safe = jnp.where(x > 0, 0.0, x)
    return _SELU_LAMBDA * jnp.where(x > 0, x, _SELU_ALPHA * (jnp.exp(safe) - 1.0))


def gelu(x: Array) -> Array:
    # exact (erf-based) gelu — what keras/tf mean by "gelu"; the tanh
    # approximation is registered separately as "gelu_tanh". (Renamed before
    # any released checkpoint serialized "gelu": no committed artifact —
    # fixtures included — references it, so restore semantics are unchanged.)
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x: Array) -> Array:
    # tanh approximation (the original BERT formulation)
    return 0.5 * x * (1.0 + jnp.tanh(math.sqrt(2.0 / math.pi) * (x + 0.044715 * x**3)))


def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


def hardsigmoid(x: Array) -> Array:
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x: Array) -> Array:
    return jnp.tanh(x)


def hardtanh(x: Array) -> Array:
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x: Array) -> Array:
    # DL4J RATIONALTANH: 1.7159 * tanh(2x/3) approximated rationally; we use
    # the exact functional form (the rational approximation was a CPU speed
    # hack, irrelevant on TPU).
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def rectifiedtanh(x: Array) -> Array:
    return jnp.maximum(0.0, jnp.tanh(x))


def softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


def logsoftmax(x: Array) -> Array:
    return jax.nn.log_softmax(x, axis=-1)


def softplus(x: Array) -> Array:
    return jax.nn.softplus(x)


def softsign(x: Array) -> Array:
    return x / (1.0 + jnp.abs(x))


def cube(x: Array) -> Array:
    return x**3


def swish(x: Array) -> Array:
    return x * jax.nn.sigmoid(x)


def mish(x: Array) -> Array:
    return x * jnp.tanh(jax.nn.softplus(x))


def thresholdedrelu(x: Array, theta: float = 1.0) -> Array:
    return jnp.where(x > theta, x, 0.0)


_REGISTRY: dict[str, ActivationFn] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "gelu_tanh": gelu_tanh,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "swish": swish,
    "mish": mish,
    "thresholdedrelu": thresholdedrelu,
}


def names() -> list[str]:
    return sorted(_REGISTRY)


def resolve(activation: Union[str, ActivationFn, tuple, None]) -> ActivationFn:
    """Resolve an activation spec to a callable.

    Accepts a name (``"relu"``), a ``(name, kwargs)`` tuple for parametric
    activations (``("leakyrelu", {"alpha": 0.2})``), an existing callable, or
    ``None`` (identity).
    """
    if activation is None:
        return identity
    if callable(activation):
        return activation
    if isinstance(activation, tuple):
        name, kwargs = activation
        fn = _REGISTRY[name.lower()]
        return lambda x: fn(x, **kwargs)
    key = activation.lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation {activation!r}; known: {names()}")
    return _REGISTRY[key]
