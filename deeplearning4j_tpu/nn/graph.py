"""ComputationGraph — DAG model with multiple inputs/outputs, TPU-native.

Reference: ``nn/graph/ComputationGraph.java`` (3.9k LoC): topological
execution (``topologicalOrder:152``), ``fit(DataSetIterator):886`` /
``fit(MultiDataSetIterator):1010``, ``output``, ``rnnTimeStep``, evaluation.

TPU design mirrors MultiLayerNetwork: params are a dict keyed by vertex name,
the whole train step (forward over the topo order, summed output losses,
``jax.grad``, updaters) is ONE jitted donated-buffer function. Vertices are
pure functions, so the DAG is just function composition — XLA sees a single
fused program, not an object graph.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.constraints import apply_constraints
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer, check_carry_capacity
from deeplearning4j_tpu.nn.updaters import Sgd, Updater, normalize_gradients

Array = jax.Array
Params = Dict[str, Dict[str, Array]]
States = Dict[str, Dict[str, Array]]


def _as_jnp(x, dtype=None):
    if isinstance(x, (np.ndarray, list, tuple)) or np.isscalar(x):
        x = jnp.asarray(x)
    if dtype is not None and jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dtype:
        x = x.astype(dtype)
    return x


class ComputationGraph:
    """DAG network over a ComputationGraphConfiguration."""

    # set by parallel.sharding.shard_model_with_rules: when present, fit()/
    # output() place incoming batches over the mesh's data axis so pjit sees
    # a consistent DP x MP layout end to end (GSPMD handles the rest), and
    # the train step pins updated params/opt-state back to the placed specs
    _mesh = None
    _param_shardings = None
    _upd_shardings = None

    def _pin_placements(self, new_params, new_upd):
        """Inside-jit: constrain step outputs to the rule-placed shardings
        (see MultiLayerNetwork._pin_placements — one GSPMD-drifted leaf
        re-layouts every later compile)."""
        if self._param_shardings is not None:
            new_params = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_params,
                self._param_shardings)
        if self._upd_shardings is not None and new_upd is not None:
            new_upd = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_upd,
                self._upd_shardings)
        return new_params, new_upd

    def __init__(self, conf: ComputationGraphConfiguration):
        conf.finalize()
        self.conf = conf
        self.params: Optional[Params] = None
        self.states: Optional[States] = None
        self.updater_states: Optional[Dict[str, Dict[str, Dict[str, Array]]]] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._score_arr = None
        self._rng_key: Optional[jax.Array] = None
        self._jit_cache: Dict[Any, Any] = {}
        self._updaters: Dict[str, Dict[str, Updater]] = {}
        self._rnn_carries: Optional[Dict[str, Any]] = None
        self._rnn_pos = 0
        # cumulative host→device batch payload shipped by fit(); the
        # TraceListener exports deltas as training_transfer_bytes_total
        self.transfer_bytes = 0

    # ---------------------------------------------------------------- score
    @property
    def score_(self) -> float:
        return float("nan") if self._score_arr is None else float(self._score_arr)

    @score_.setter
    def score_(self, v) -> None:
        self._score_arr = v

    # ----------------------------------------------------------------- init
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        g = self.conf.global_conf
        key = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng_key = jax.random.fold_in(key, 0x5EED)
        dtype = g.jnp_dtype()
        self.params, self.states = {}, {}
        self._updaters, self.updater_states = {}, {}
        default_updater = g.updater or Sgd(0.1)
        layer_defs = self.conf.layer_vertices()
        keys = jax.random.split(key, max(1, len(layer_defs)))
        for vd, k in zip(layer_defs, keys):
            layer: Layer = vd.obj  # type: ignore[assignment]
            p = layer.init_params(k, dtype)
            self.params[vd.name] = p
            self.states[vd.name] = layer.init_state()
            layer_upd = layer.updater or default_updater
            bias_upd = layer.bias_updater or g.bias_updater or layer_upd
            umap, smap = {}, {}
            for n, v in p.items():
                u = bias_upd if n == "b" else layer_upd
                umap[n] = u
                smap[n] = u.init_state(v)
            self._updaters[vd.name] = umap
            self.updater_states[vd.name] = smap
        self.iteration = 0
        self.epoch = 0
        return self

    def _device_tick(self):
        from deeplearning4j_tpu.nn.tick import device_tick
        return device_tick(self)

    def _store_tick(self, new_it, new_rng) -> None:
        from deeplearning4j_tpu.nn.tick import store_tick
        store_tick(self, new_it, new_rng)

    def _next_rng(self) -> jax.Array:
        self._rng_key, k = jax.random.split(self._rng_key)
        return k

    # -------------------------------------------------------------- forward
    def _forward_all(self, params: Params, states: States,
                     inputs: Dict[str, Array], *, train: bool,
                     rng: Optional[jax.Array],
                     masks: Optional[Dict[str, Optional[Array]]] = None,
                     carries: Optional[Dict[str, Any]] = None,
                     stop_at_loss: bool = True,
                     ) -> Tuple[Dict[str, Array], States,
                                Dict[str, Optional[Array]], Optional[Dict[str, Any]]]:
        """Execute the DAG in topo order.

        Returns (activations, new_states, masks, new_carries). When
        ``stop_at_loss``, output-layer vertices store their *input* (merged)
        activation under ``name + ':in'`` and their own activation is the
        layer forward (useful for output()).
        """
        conf = self.conf
        cd = conf.global_conf.jnp_compute_dtype()
        if cd is not None:
            # mixed precision: f32 master params, compute-dtype forward
            cast = lambda a: (a.astype(cd)
                              if hasattr(a, "dtype")
                              and jnp.issubdtype(a.dtype, jnp.floating) else a)
            params = jax.tree_util.tree_map(cast, params)
            inputs = {k: cast(v) for k, v in inputs.items()}
        acts: Dict[str, Array] = dict(inputs)
        m: Dict[str, Optional[Array]] = dict(masks or {})
        for name in conf.inputs:
            m.setdefault(name, None)
        new_states: States = {}
        new_carries: Dict[str, Any] = {}
        n_layers = max(1, len(conf.topo_order))
        rngs = (jax.random.split(rng, n_layers) if rng is not None else [None] * n_layers)
        for vi, name in enumerate(conf.topo_order):
            vd = conf.vertices[name]
            in_acts = [acts[s] for s in vd.inputs]
            in_masks = [m.get(s) for s in vd.inputs]
            if vd.is_layer:
                layer: Layer = vd.obj  # type: ignore[assignment]
                p_v, rng_v = params[name], rngs[vi]
                if (getattr(layer, "weight_noise", None) is not None and train
                        and rng_v is not None):
                    # train-time weight noise (DropConnect.java:19, MLN
                    # parity) — applied before BOTH the single- and
                    # multi-input forward paths
                    rng_wn, rng_v = jax.random.split(rng_v)
                    p_v = layer.weight_noise.apply(layer, p_v, rng_wn, train)
                if getattr(layer, "consumes_multiple_inputs", False):
                    y, st = layer.forward_multi(
                        p_v, in_acts, state=states[name], train=train,
                        rng=rng_v, masks=in_masks)
                    new_states[name] = st if st else states[name]
                    acts[name] = y
                    m[name] = in_masks[0]
                    continue
                h = in_acts[0] if len(in_acts) == 1 else jnp.concatenate(in_acts, -1)
                if name in conf.preprocessors:
                    h = conf.preprocessors[name](h)
                cur_mask = in_masks[0]
                if layer.has_loss():
                    acts[name + ":in"] = h
                    acts[name + ":mask"] = cur_mask
                if carries is not None and isinstance(layer, BaseRecurrentLayer):
                    y, c = layer.forward_seq(p_v, h, carry=carries.get(name),
                                             mask=cur_mask, train=train, rng=rng_v)
                    new_states[name] = states[name]
                    new_carries[name] = c
                    acts[name] = y
                else:
                    fwd = lambda p, hh, _l=layer, _n=name, _r=rng_v: _l.forward(
                        p, hh, state=states[_n], train=train, rng=_r,
                        mask=cur_mask)
                    if train and conf.global_conf.gradient_checkpointing:
                        # rematerialize activations in the backward pass
                        fwd = jax.checkpoint(fwd)
                    y, st = fwd(p_v, h)
                    new_states[name] = st if st else states[name]
                    acts[name] = y
                # per-timestep mask collapses when the time dim disappears;
                # per-example [N]/[N,1] masks survive (MLN parity)
                if (cur_mask is not None and acts[name].ndim == 2
                        and cur_mask.ndim == 2 and cur_mask.shape[1] > 1):
                    m[name] = None
                else:
                    m[name] = cur_mask
            else:
                acts[name] = vd.obj.forward(in_acts, in_masks)  # type: ignore[union-attr]
                m[name] = vd.obj.output_mask(in_masks)  # type: ignore[union-attr]
        return acts, new_states, m, (new_carries if carries is not None else None)

    def _regularization(self, params: Params) -> Array:
        reg = jnp.asarray(0.0, jnp.float32)
        for vd in self.conf.layer_vertices():
            l: Layer = vd.obj  # type: ignore[assignment]
            for n, v in params[vd.name].items():
                is_bias = n == "b"
                l1 = (l.l1_bias if is_bias else l.l1) or 0.0
                l2 = (l.l2_bias if is_bias else l.l2) or 0.0
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(v))
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(v * v)
        return reg

    def _loss_fn(self, params: Params, states: States,
                 inputs: Dict[str, Array], labels: Sequence[Array],
                 rng, masks, label_masks, train: bool, carries=None):
        acts, new_states, out_masks, new_carries = self._forward_all(
            params, states, inputs, train=train, rng=rng, masks=masks,
            carries=carries)
        loss = jnp.asarray(0.0, jnp.float32)
        for oi, out_name in enumerate(self.conf.outputs):
            vd = self.conf.vertices[out_name]
            layer = vd.obj
            if not (vd.is_layer and layer.has_loss()):
                raise ValueError(f"output vertex {out_name!r} is not a loss layer")
            h = acts[out_name + ":in"]
            if self.conf.global_conf.compute_dtype is not None:
                # loss head in f32 for stable softmax/log under mixed precision
                h = h.astype(jnp.float32)
            lm = None
            if label_masks is not None and label_masks[oi] is not None:
                lm = label_masks[oi]
            elif h.ndim == 3:
                lm = acts.get(out_name + ":mask")
            else:
                fm = acts.get(out_name + ":mask")
                if fm is not None and (fm.ndim == 1 or
                                       (fm.ndim == 2 and fm.shape[-1] == 1)):
                    # per-example feature mask masks the score (MLN parity)
                    lm = fm.reshape(fm.shape[0])
            p_out = params[out_name]
            if (getattr(layer, "weight_noise", None) is not None and train
                    and rng is not None):
                # output layers get weight noise too (MLN parity). Re-derive
                # the SAME key the vertex loop used for this vertex so the
                # loss sees the identical noised weights as any downstream
                # consumer of the output vertex's activation — one noise
                # sample per layer per step.
                topo = self.conf.topo_order
                vi = topo.index(out_name)
                rng_v = jax.random.split(rng, max(1, len(topo)))[vi]
                rng_wn = jax.random.split(rng_v)[0]
                p_out = layer.weight_noise.apply(layer, p_out, rng_wn, train)
            loss = loss + layer.compute_loss(p_out, h, labels[oi], mask=lm)
        loss = loss + self._regularization(params)
        return loss, (new_states, new_carries)

    # ------------------------------------------------------------ train step
    def _apply_updates(self, params, grads, upd_states, it, ep):
        # "updater" helper seam (see MultiLayerNetwork._apply_updates):
        # a registered fused kernel takes the whole per-param RMW
        from deeplearning4j_tpu.nn import helpers as _helpers
        uhelper = _helpers.get_helper("updater")
        new_params: Params = {}
        new_upd = {}
        for vd in self.conf.layer_vertices():
            name = vd.name
            l: Layer = vd.obj  # type: ignore[assignment]
            g_layer = grads[name]
            if l.gradient_normalization:
                g_layer = normalize_gradients(g_layer, l.gradient_normalization,
                                              l.gradient_normalization_threshold)
            p_new, s_new = {}, {}
            for n, g in g_layer.items():
                u = self._updaters[name][n]
                lr = u.lr_at(it, ep)
                if uhelper is not None and uhelper.supports(u, params[name][n], g):
                    p_new[n], s_new[n] = uhelper.apply(
                        u, params[name][n], g, upd_states[name][n], lr,
                        it + 1.0)
                    continue
                upd, s = u.update(g, upd_states[name][n], lr, it + 1.0)
                p_new[n] = params[name][n] - upd.astype(params[name][n].dtype)
                s_new[n] = s
            # post-update constraints (BaseConstraint.applyConstraint parity)
            p_new = apply_constraints(l, p_new)
            new_params[name] = p_new
            new_upd[name] = s_new
        return new_params, new_upd

    def _evict_stale(self, current_version: int) -> None:
        from deeplearning4j_tpu.nn import helpers as _helpers
        _helpers.evict_stale_jit_entries(self._jit_cache, current_version)

    def _get_train_step(self, with_carries: bool = False):
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("train", with_carries, _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def step(params, states, upd_states, it, ep, inputs, labels,
                     masks, label_masks, rng, carries=None):
                # on-device key split + returned (it+1, next key): the fit
                # loop re-feeds them with zero per-step host-side device
                # ops (worth ~14 ms/step over a remote dispatch link)
                rng_use, rng_next = jax.random.split(rng)

                def lf(p):
                    return self._loss_fn(p, states, inputs, labels, rng_use,
                                         masks, label_masks, train=True,
                                         carries=carries)
                from deeplearning4j_tpu.nn.tick import schedule_tick
                with schedule_tick(it, ep):  # dropout pSchedule sees the tick
                    (loss, (new_states, new_carries)), grads = \
                        jax.value_and_grad(lf, has_aux=True)(params)
                new_params, new_upd = self._apply_updates(params, grads, upd_states, it, ep)
                new_params, new_upd = self._pin_placements(new_params, new_upd)
                return (new_params, new_states, new_upd, loss, new_carries,
                        it + 1.0, rng_next)

            self._jit_cache[key] = jax.jit(step, donate_argnums=(0, 1, 2, 3, 9))
        return self._jit_cache[key]

    def _get_multi_train_step(self):
        """K train steps as ONE compiled ``lax.scan`` over stacked batches —
        a single dispatch executes the whole window on device. This is the
        TPU training-loop idiom: per-step host dispatch (milliseconds over a
        remote link) disappears, and XLA pipelines the step boundary."""
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("train_scan", _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def multi(params, states, upd_states, it0, ep, inputs_s,
                      labels_s, rng0):
                def body(carry, xs):
                    params, states, upd, it, rng = carry
                    inputs, labels = xs
                    rng, sub = jax.random.split(rng)
                    def lf(p):
                        return self._loss_fn(p, states, inputs, labels, sub,
                                             None, None, train=True)
                    from deeplearning4j_tpu.nn.tick import schedule_tick
                    with schedule_tick(it, ep):
                        (loss, (new_states, _)), grads = jax.value_and_grad(
                            lf, has_aux=True)(params)
                    new_params, new_upd = self._apply_updates(
                        params, grads, upd, it, ep)
                    new_params, new_upd = self._pin_placements(new_params,
                                                               new_upd)
                    return (new_params, new_states, new_upd, it + 1.0, rng), loss

                (params, states, upd, _, _), losses = jax.lax.scan(
                    body, (params, states, upd_states, it0, rng0),
                    (inputs_s, labels_s))
                return params, states, upd, losses

            self._jit_cache[key] = jax.jit(multi, donate_argnums=(0, 1, 2))
        return self._jit_cache[key]

    def fit_batches_on_device(self, datasets) -> "ComputationGraph":
        """Train on a window of equal-shape batches in ONE device dispatch
        (``lax.scan`` over the stacked window). Semantically identical to
        calling ``fit`` once per batch; built for dispatch-bound setups
        where per-step host→device latency is significant. Requires uniform
        shapes, no masks, standard backprop.

        Caveat measured on tunneled/virtualized chips (axon): backends that
        stream operands lazily can make the stacked window catastrophically
        slower than per-step dispatch — use on directly-attached hardware.
        """
        from deeplearning4j_tpu.nn.conf.network import normalize_backprop_type
        if self.params is None:
            self.init()
        if normalize_backprop_type(self.conf.backprop_type) != "standard":
            raise ValueError("fit_batches_on_device supports standard "
                             "backprop only (not TBPTT)")
        mds_list = [self._to_mds(ds) for ds in datasets]
        if not mds_list:
            return self
        for m in mds_list:
            if m.features_masks is not None or m.labels_masks is not None:
                raise ValueError("fit_batches_on_device does not carry masks")
        dtype = self.conf.global_conf.jnp_dtype()
        inputs_s = {n: jnp.stack([_as_jnp(m.features[i], dtype)
                                  for m in mds_list])
                    for i, n in enumerate(self.conf.inputs)}
        labels_s = [jnp.stack([_as_jnp(m.labels[i], dtype) for m in mds_list])
                    for i in range(len(mds_list[0].labels))]
        k = len(mds_list)
        multi = self._get_multi_train_step()
        it0 = jnp.asarray(self.iteration, jnp.float32)
        ep = jnp.asarray(self.epoch, jnp.float32)
        (self.params, self.states, self.updater_states, losses) = multi(
            self.params, self.states, self.updater_states, it0, ep,
            inputs_s, labels_s, self._next_rng())
        self.last_batch_size = int(next(iter(inputs_s.values())).shape[1])
        # listeners see every iteration with its own loss, exactly like K
        # sequential fit calls (the device already ran them all)
        for i in range(k):
            self._score_arr = losses[i]
            self.iteration += 1
            for listener in self.listeners:
                if hasattr(listener, "iteration_done"):
                    listener.iteration_done(self, self.iteration, self.epoch)
        return self

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            prefetch_depth: Optional[int] = None) -> "ComputationGraph":
        """Train. Iterator sources are auto-wrapped in async host→device
        prefetch (see MultiLayerNetwork.fit): ``prefetch_depth`` queue
        slots (default 2), 0 disables, ``async_supported = False`` opts
        out; ``host_wait`` span + ``training_transfer_bytes_total`` expose
        any residual input-pipeline stall."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.datasets.dataset import (DataSet,
                                                         MultiDataSet,
                                                         batch_nbytes)
        from deeplearning4j_tpu.datasets.iterators import wrap_for_prefetch
        from deeplearning4j_tpu.observe import trace as _trace

        if labels is not None:
            iterator = [MultiDataSet(
                data if isinstance(data, (list, tuple)) else [data],
                labels if isinstance(labels, (list, tuple)) else [labels])]
        elif isinstance(data, (DataSet, MultiDataSet)):
            iterator = [data]
        else:
            iterator = data
        iterator = wrap_for_prefetch(iterator, prefetch_depth)

        for _ in range(epochs):
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(self)
            if hasattr(iterator, "reset"):
                iterator.reset()
            batches = iter(iterator)
            while True:
                with _trace.span("host_wait", category="train"):
                    ds = next(batches, None)
                if ds is None:
                    break
                self.transfer_bytes += batch_nbytes(ds)
                self._fit_batch(ds)
            self.epoch += 1
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
        return self

    def _to_mds(self, ds):
        from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
        if isinstance(ds, DataSet):
            return MultiDataSet(
                [ds.features], [ds.labels],
                None if ds.features_mask is None else [ds.features_mask],
                None if ds.labels_mask is None else [ds.labels_mask])
        return ds

    def _fit_batch(self, ds) -> None:
        mds = self._to_mds(ds)
        dtype = self.conf.global_conf.jnp_dtype()
        inputs = {n: _as_jnp(f, dtype) for n, f in zip(self.conf.inputs, mds.features)}
        labels = [_as_jnp(l, dtype) for l in mds.labels]
        masks = None
        if mds.features_masks is not None:
            masks = {n: (None if m is None else _as_jnp(m))
                     for n, m in zip(self.conf.inputs, mds.features_masks)}
        lmasks = None
        if mds.labels_masks is not None:
            lmasks = [None if m is None else _as_jnp(m) for m in mds.labels_masks]
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import place_batch
            mesh = self._mesh
            inputs, labels, masks, lmasks = jax.tree_util.tree_map(
                lambda a: place_batch(a, mesh), (inputs, labels, masks, lmasks))

        from deeplearning4j_tpu.nn.conf.network import normalize_backprop_type
        if normalize_backprop_type(self.conf.backprop_type) == "truncated_bptt":
            t_total = self._temporal_length(inputs)
            if t_total is not None:
                self._fit_tbptt(inputs, labels, masks, lmasks, t_total)
                return

        step = self._get_train_step()
        it, ep, rng = self._device_tick()
        (self.params, self.states, self.updater_states, loss, _,
         new_it, new_rng) = step(
            self.params, self.states, self.updater_states, it, ep,
            inputs, labels, masks, lmasks, rng)
        self._score_arr = loss
        self.last_batch_size = int(next(iter(inputs.values())).shape[0])
        self.iteration += 1
        self._store_tick(new_it, new_rng)
        for listener in self.listeners:
            if hasattr(listener, "iteration_done"):
                listener.iteration_done(self, self.iteration, self.epoch)

    def _temporal_inputs(self, inputs) -> set:
        """Input names carrying a time axis: decided by the declared
        InputTypes when present (rnn / image-sequence), else by rank."""
        kinds = ("rnn", "cnn_seq")
        if (self.conf.input_types
                and len(self.conf.input_types) == len(self.conf.inputs)
                and all(t is not None for t in self.conf.input_types)):
            return {n for n, t in zip(self.conf.inputs, self.conf.input_types)
                    if t.kind in kinds}
        return {n for n, a in inputs.items() if a.ndim == 3}

    def _temporal_length(self, inputs):
        ts = {inputs[n].shape[1] for n in self._temporal_inputs(inputs)}
        if len(ts) > 1:
            raise ValueError(f"temporal inputs disagree on sequence length: {ts}")
        return ts.pop() if ts else None

    def _fit_tbptt(self, inputs, labels, masks, lmasks, t_total) -> None:
        """Truncated BPTT over the DAG (ComputationGraph's TBPTT dispatch in
        the reference fit loop): slice the declared-temporal inputs (and
        per-timestep labels/masks) into tbptt_fwd_length chunks, carrying
        recurrent state (KV caches, positional offsets, LSTM carries)
        between the jitted chunk steps. Per-sequence (2D) labels are fed
        whole to every chunk, as in the sequential-network TBPTT."""
        check_carry_capacity(
            ((vd.name, vd.obj) for vd in self.conf.layer_vertices()),
            t_total, "TBPTT")
        temporal = self._temporal_inputs(inputs)
        length = self.conf.tbptt_fwd_length
        n_chunks = max(1, math.ceil(t_total / length))
        batch = next(iter(inputs.values())).shape[0]
        self.last_batch_size = int(batch)
        dtype = self.conf.global_conf.jnp_dtype()
        carries = {vd.name: vd.obj.init_carry(batch, dtype)
                   for vd in self.conf.layer_vertices()
                   if isinstance(vd.obj, BaseRecurrentLayer)}

        step = self._get_train_step(True)
        for c in range(n_chunks):
            s, e = c * length, min((c + 1) * length, t_total)
            ic = {n: (a[:, s:e] if n in temporal else a)
                  for n, a in inputs.items()}
            lc = [a[:, s:e] if a.ndim == 3 and a.shape[1] == t_total else a
                  for a in labels]
            mc = None if masks is None else {
                n: (a[:, s:e] if a is not None and n in temporal
                    and a.shape[1] == t_total else a)
                for n, a in masks.items()}
            lmc = None if lmasks is None else [
                a[:, s:e] if a is not None and labels[i].ndim == 3
                and a.shape[1] == t_total else a
                for i, a in enumerate(lmasks)]
            it, ep, rng = self._device_tick()
            (self.params, self.states, self.updater_states, loss, carries,
             new_it, new_rng) = \
                step(self.params, self.states, self.updater_states, it, ep,
                     ic, lc, mc, lmc, rng, carries)
            self._score_arr = loss
            self.iteration += 1
            self._store_tick(new_it, new_rng)
        for listener in self.listeners:
            if hasattr(listener, "iteration_done"):
                listener.iteration_done(self, self.iteration, self.epoch)

    # ------------------------------------------------------------- inference
    def _output_fn(self):
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("out", _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def out_fn(params, states, inputs, masks):
                acts, _, _, _ = self._forward_all(params, states, inputs,
                                                  train=False, rng=None, masks=masks)
                return [acts[n] for n in self.conf.outputs]
            self._jit_cache[key] = jax.jit(out_fn)
        return self._jit_cache[key]

    def output(self, *xs, masks=None) -> Union[Array, List[Array]]:
        dtype = self.conf.global_conf.jnp_dtype()
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
            xs = tuple(xs[0])
        inputs = {n: _as_jnp(x, dtype) for n, x in zip(self.conf.inputs, xs)}
        mask_d = None
        if masks is not None:
            mask_d = {n: (None if m is None else _as_jnp(m))
                      for n, m in zip(self.conf.inputs, masks)}
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import place_batch
            mesh = self._mesh
            inputs, mask_d = jax.tree_util.tree_map(
                lambda a: place_batch(a, mesh), (inputs, mask_d))
        outs = self._output_fn()(self.params, self.states, inputs, mask_d)
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, *xs, train: bool = False) -> Dict[str, Array]:
        dtype = self.conf.global_conf.jnp_dtype()
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
            xs = tuple(xs[0])
        inputs = {n: _as_jnp(x, dtype) for n, x in zip(self.conf.inputs, xs)}
        acts, _, _, _ = self._forward_all(self.params, self.states, inputs,
                                          train=train, rng=None)
        return {k: v for k, v in acts.items() if ":" not in k}

    def predict(self, *xs) -> np.ndarray:
        out = self.output(*xs)
        if isinstance(out, list):
            out = out[0]
        return np.asarray(jnp.argmax(out, axis=-1))

    def score(self, ds=None) -> float:
        if ds is None:
            return self.score_
        mds = self._to_mds(ds)
        dtype = self.conf.global_conf.jnp_dtype()
        inputs = {n: _as_jnp(f, dtype) for n, f in zip(self.conf.inputs, mds.features)}
        labels = [_as_jnp(l, dtype) for l in mds.labels]
        loss, _ = self._loss_fn(self.params, self.states, inputs, labels,
                                None, None, None, train=False)
        return float(loss)

    def compute_gradient_and_score(self, features, labels):
        """Gradient-check hook (GradientCheckUtil parity for graphs)."""
        mds = self._to_mds(self._wrap(features, labels))
        dtype = self.conf.global_conf.jnp_dtype()
        inputs = {n: _as_jnp(f, dtype) for n, f in zip(self.conf.inputs, mds.features)}
        labs = [_as_jnp(l, dtype) for l in mds.labels]

        def lf(p):
            return self._loss_fn(p, self.states, inputs, labs, None, None, None,
                                 train=False)

        (loss, _), grads = jax.value_and_grad(lf, has_aux=True)(self.params)
        return grads, float(loss)

    def _wrap(self, features, labels):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        return MultiDataSet(
            features if isinstance(features, (list, tuple)) else [features],
            labels if isinstance(labels, (list, tuple)) else [labels])

    # ------------------------------------------------------ stateful RNN API
    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None
        self._rnn_pos = 0

    def _rnn_step_fn(self):
        """Jitted stateful step: the whole per-chunk forward (KV-cache
        writes included) compiles to ONE executable per input shape, so
        autoregressive decoding is a jitted step per token, not per-op
        Python dispatch."""
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("rnn_step", _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def step_fn(params, states, inputs, carries):
                acts, _, _, new_carries = self._forward_all(
                    params, states, inputs, train=False, rng=None,
                    carries=carries)
                return [acts[n] for n in self.conf.outputs], new_carries
            self._jit_cache[key] = jax.jit(step_fn)
        return self._jit_cache[key]

    def rnn_time_step(self, *xs) -> Union[Array, List[Array]]:
        dtype = self.conf.global_conf.jnp_dtype()
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
            xs = tuple(xs[0])
        xs = [_as_jnp(x, dtype) for x in xs]
        squeeze = xs[0].ndim == 2
        if squeeze:
            xs = [x[:, None, :] for x in xs]
        if self._rnn_carries is None:
            batch = xs[0].shape[0]
            self._rnn_carries = {}
            self._rnn_pos = 0
            for vd in self.conf.layer_vertices():
                if isinstance(vd.obj, BaseRecurrentLayer):
                    self._rnn_carries[vd.name] = vd.obj.init_carry(batch, dtype)
        # finite carries (KV caches, positional offsets) cannot raise inside
        # the jitted step — enforce capacity host-side
        t_new = xs[0].shape[1]
        check_carry_capacity(
            ((vd.name, vd.obj) for vd in self.conf.layer_vertices()),
            self._rnn_pos + t_new,
            f"rnn_time_step at position {self._rnn_pos}+{t_new}")
        inputs = dict(zip(self.conf.inputs, xs))
        outs, self._rnn_carries = self._rnn_step_fn()(
            self.params, self.states, inputs, self._rnn_carries)
        self._rnn_pos += t_new
        if squeeze:
            outs = [o[:, -1, :] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    # ------------------------------------------------------------ evaluation
    # ------------------------------------------------------------- pretrain
    def pretrain_layer(self, vertex_name: str, data, epochs: int = 1
                       ) -> "ComputationGraph":
        """Unsupervised pretraining of one layer vertex
        (``ComputationGraph.pretrainLayer``): the vertex's input activation
        is featurized with the rest of the graph frozen, then its own
        ``pretrain_loss`` is minimized with its configured updater."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if self.params is None:
            self.init()
        vd = self.conf.vertices[vertex_name]
        layer = vd.obj if vd.is_layer else None
        if layer is None or not hasattr(layer, "pretrain_loss"):
            raise ValueError(
                f"vertex {vertex_name!r} is not a pretrainable layer "
                "(needs pretrain_loss — VAE/autoencoder)")
        if hasattr(data, "features") or hasattr(data, "shape"):
            iterator = [data if hasattr(data, "features")
                        else DataSet(data, data)]
        else:
            iterator = data
        dtype = self.conf.global_conf.jnp_dtype()

        def step(p_v, upd_v, it, h, rng):
            loss, grads = jax.value_and_grad(
                lambda p: layer.pretrain_loss(p, h, rng))(p_v)
            new_p, new_upd = {}, {}
            for n, g in grads.items():
                u = self._updaters[vertex_name][n]
                lr = u.lr_at(it, 0.0)
                delta, s = u.update(g, upd_v[n], lr, it + 1.0)
                new_p[n] = p_v[n] - delta.astype(p_v[n].dtype)
                new_upd[n] = s
            return new_p, new_upd, loss

        jstep = jax.jit(step, donate_argnums=(0, 1))
        it_count = 0
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                mds = self._to_mds(ds)
                inputs = {n: _as_jnp(f, dtype)
                          for n, f in zip(self.conf.inputs, mds.features)}
                acts, _, _, _ = self._forward_all(
                    self.params, self.states, inputs, train=False, rng=None)
                ins = [acts[s] for s in vd.inputs]
                h = ins[0] if len(ins) == 1 else jnp.concatenate(ins, -1)
                (self.params[vertex_name],
                 self.updater_states[vertex_name], loss) = jstep(
                    self.params[vertex_name],
                    self.updater_states[vertex_name],
                    jnp.asarray(float(it_count), jnp.float32), h,
                    self._next_rng())
                it_count += 1
                self._score_arr = loss
        return self

    def pretrain(self, data, epochs: int = 1) -> "ComputationGraph":
        """Layer-wise pretraining over every pretrainable vertex in
        topological order (``ComputationGraph.pretrain``)."""
        if self.params is None:
            self.init()
        for name in self.conf.topo_order:
            vd = self.conf.vertices[name]
            if vd.is_layer and hasattr(vd.obj, "pretrain_loss"):
                self.pretrain_layer(name, data, epochs=epochs)
        return self

    def _eval_first_output(self, iterator, consume) -> None:
        """One evaluate loop for every evaluator: reset, convert to
        MultiDataSet, forward the FIRST output with features masks
        applied, then hand (labels, out, label_mask, ds) to ``consume``.
        Keeping a single code path prevents the evaluators from drifting
        apart on mask handling."""
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            mds = self._to_mds(ds)
            out = self.output(*mds.features, masks=mds.features_masks)
            if isinstance(out, list):
                out = out[0]
            lm = (None if mds.labels_masks is None
                  else mds.labels_masks[0])
            consume(np.asarray(mds.labels[0]), np.asarray(out),
                    None if lm is None else np.asarray(lm), ds)

    def evaluate(self, iterator, top_n: int = 1) -> "Evaluation":
        """Evaluate the first output over an iterator
        (``ComputationGraph.evaluate``); ``top_n`` and collected record
        metadata flow through exactly as in MultiLayerNetwork.evaluate."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation(top_n=top_n)
        self._eval_first_output(
            iterator,
            lambda labels, out, lm, ds: e.eval(
                labels, out, mask=lm,
                record_meta_data=getattr(ds, "example_meta_data", None)))
        return e

    def summary(self) -> str:
        """Vertex table with parameter counts
        (``ComputationGraph.summary()``)."""
        if self.params is None:
            self.init()
        rows = []
        total = 0
        for name in self.conf.topo_order:
            vd = self.conf.vertices[name]
            if vd.is_layer:
                p = self.params.get(name, {})
                n = sum(int(np.prod(v.shape)) for v in p.values())
                total += n
                kind = type(vd.obj).__name__
            else:
                n, kind = 0, type(vd.obj).__name__
            rows.append((name, kind, f"{n:,}", ", ".join(vd.inputs)))
        w0 = max(6, max(len(r[0]) for r in rows))
        w1 = max(10, max(len(r[1]) for r in rows))
        w2 = max(8, max(len(r[2]) for r in rows))
        lines = ["=" * 76,
                 f"{'vertex':<{w0}}  {'type':<{w1}}  {'params':>{w2}}  inputs",
                 "-" * 76]
        for r in rows:
            lines.append(f"{r[0]:<{w0}}  {r[1]:<{w1}}  {r[2]:>{w2}}  {r[3]}")
        lines += ["-" * 76, f"Total parameters: {total:,}", "=" * 76]
        return "\n".join(lines)

    def evaluate_regression(self, iterator):
        """Per-column regression metrics over the first output
        (``ComputationGraph.evaluateRegression``)."""
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        e = RegressionEvaluation()
        self._eval_first_output(
            iterator,
            lambda labels, out, lm, ds: e.eval(labels, out, mask=lm))
        return e

    def evaluate_roc(self, iterator, threshold_steps: int = 0):
        """Binary ROC over the first output (``ComputationGraph
        .evaluateROC``)."""
        from deeplearning4j_tpu.eval.roc import ROC
        r = ROC(threshold_steps=threshold_steps)
        self._eval_first_output(
            iterator,
            lambda labels, out, lm, ds: r.eval(labels, out, mask=lm))
        return r

    def evaluate_roc_multi_class(self, iterator, threshold_steps: int = 0):
        """One-vs-all ROC per class over the first output
        (``ComputationGraph.evaluateROCMultiClass``)."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        r = ROCMultiClass(threshold_steps=threshold_steps)
        self._eval_first_output(
            iterator,
            lambda labels, out, lm, ds: r.eval(labels, out, mask=lm))
        return r

    def evaluate_roc_binary(self, iterator, threshold_steps: int = 0):
        """Per-output binary ROC over the first output
        (``doEvaluation`` with ROCBinary), features and label masks
        honored."""
        from deeplearning4j_tpu.eval.roc import ROCBinary
        r = ROCBinary(threshold_steps=threshold_steps)
        self._eval_first_output(
            iterator,
            lambda labels, out, lm, ds: r.eval(labels, out, mask=lm))
        return r

    def output_single(self, *xs) -> Array:
        """First output as a single array (``outputSingle``)."""
        out = self.output(*xs)
        return out[0] if isinstance(out, list) else out

    def get_vertex(self, name: str):
        """Vertex definition by name (``getVertex``)."""
        return self.conf.vertices[name]

    def get_layer(self, name: str):
        """Layer object of a layer vertex (``getLayer``)."""
        vd = self.conf.vertices[name]
        if not vd.is_layer:
            raise KeyError(f"vertex {name!r} is not a layer vertex")
        return vd.obj

    def get_vertices(self) -> dict:
        """All vertex definitions by name (``getVertices``)."""
        return dict(self.conf.vertices)

    def get_num_layers(self) -> int:
        """Number of layer vertices (``getNumLayers``)."""
        return len(self.conf.layer_vertices())

    def get_num_input_arrays(self) -> int:
        """``getNumInputArrays``."""
        return len(self.conf.inputs)

    def get_num_output_arrays(self) -> int:
        """``getNumOutputArrays``."""
        return len(self.conf.outputs)

    def get_output_layer(self, index: int = 0):
        """Layer object of the index-th output vertex (``getOutputLayer``)."""
        name = self.conf.outputs[index]
        return self.get_layer(name)

    def topological_sort_order(self) -> list:
        """Vertex names in execution order (``topologicalSortOrder``)."""
        return list(self.conf.topo_order)

    def rnn_get_previous_state(self, name: str):
        """Stored carry of a recurrent layer vertex
        (``rnnGetPreviousState``), or None before any rnn_time_step."""
        if self._rnn_carries is None:
            return None
        return self._rnn_carries.get(name)

    def rnn_get_previous_states(self) -> dict:
        """All stored carries by vertex name (``rnnGetPreviousStates``)."""
        return dict(self._rnn_carries or {})

    def rnn_set_previous_state(self, name: str, state,
                               position: Optional[int] = None) -> None:
        """Overwrite a recurrent vertex's stored carry
        (``rnnSetPreviousState``); ``position`` (total timesteps already
        absorbed) is required when any layer has a finite carry so the
        host-side capacity guard stays in sync with the restored cache."""
        if self._rnn_carries is None:
            raise ValueError(
                "no stored rnn state to overwrite; call rnn_time_step "
                "first to initialize the carries")
        if position is not None:
            self._rnn_pos = int(position)
        else:
            from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer
            finite = any(
                vd.is_layer and isinstance(vd.obj, BaseRecurrentLayer)
                and vd.obj.carry_capacity() is not None
                for vd in self.conf.vertices.values())
            if finite:
                raise ValueError(
                    "rnn_set_previous_state needs position= when a layer "
                    "has a finite carry capacity (KV cache)")
        self._rnn_carries[name] = state

    def rnn_set_previous_states(self, states: dict,
                                position: Optional[int] = None) -> None:
        """Overwrite several carries at once (``rnnSetPreviousStates``)."""
        for name, state in states.items():
            self.rnn_set_previous_state(name, state, position=position)

    def get_layers(self) -> list:
        """All layer objects in topological order (``getLayers``)."""
        return [vd.obj for vd in self.conf.layer_vertices()]

    def param_table(self) -> dict:
        """All parameters keyed ``"<vertexName>_<param>"``
        (``paramTable()``), e.g. ``"dense0_W"``."""
        out = {}
        for vname, p in (self.params or {}).items():
            for pname, arr in p.items():
                out[f"{vname}_{pname}"] = arr
        return out

    def get_param(self, key: str) -> Array:
        """One parameter by ``"<vertexName>_<param>"`` key (``getParam``).
        The vertex name is matched longest-first since names may contain
        underscores."""
        vname, pname = self._split_param_key(key)
        return self.params[vname][pname]

    def set_param(self, key: str, value) -> None:
        """Replace one parameter (``setParam``); shape must match."""
        vname, pname = self._split_param_key(key)
        old = self.params[vname][pname]
        arr = jnp.asarray(value, old.dtype)
        if arr.shape != old.shape:
            raise ValueError(
                f"shape mismatch for {key}: {arr.shape} vs {old.shape}")
        self.params[vname] = {**self.params[vname], pname: arr}

    def _split_param_key(self, key: str):
        for vname in sorted(self.params or {}, key=len, reverse=True):
            prefix = f"{vname}_"
            if key.startswith(prefix) and key[len(prefix):] in self.params[vname]:
                return vname, key[len(prefix):]
        raise KeyError(f"no parameter {key!r}")

    def save(self, path: str, save_updater: bool = True) -> None:
        """Write this graph as a checkpoint zip (``ComputationGraph.save``)."""
        from deeplearning4j_tpu.util import model_serializer
        model_serializer.write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "ComputationGraph":
        """Restore from a checkpoint zip (``ComputationGraph.load``)."""
        from deeplearning4j_tpu.util import model_serializer
        return model_serializer.restore_computation_graph(
            path, load_updater=load_updater)

    def layer_size(self, name: str) -> int:
        """Output size of a layer vertex (``layerSize``)."""
        vd = self.conf.vertices[name]
        n = getattr(vd.obj, "n_out", None) if vd.is_layer else None
        if n:
            return int(n)
        p = (self.params or {}).get(name, {})
        if "W" in p:
            return int(p["W"].shape[-1])
        raise ValueError(f"vertex {name!r} has no defined output size")

    def set_learning_rate(self, lr) -> None:
        """Runtime LR override for every updater (``setLearningRate``);
        rebuilds the frozen updater dataclasses and invalidates the jit
        cache (momentum/state carries over)."""
        import dataclasses as _dc
        rep = lambda u: (_dc.replace(u, learning_rate=lr)
                         if hasattr(u, "learning_rate") else u)
        self._updaters = {
            name: {n: rep(u) for n, u in umap.items()}
            for name, umap in self._updaters.items()}
        for vd in self.conf.layer_vertices():
            if vd.obj.updater is not None and hasattr(
                    vd.obj.updater, "learning_rate"):
                vd.obj.updater = _dc.replace(vd.obj.updater,
                                             learning_rate=lr)
        g = self.conf.global_conf
        if g.updater is not None and hasattr(g.updater, "learning_rate"):
            g.updater = _dc.replace(g.updater, learning_rate=lr)
        self._jit_cache.clear()

    def score_examples(self, ds, add_regularization: bool = False
                       ) -> np.ndarray:
        """Per-example losses over the first labels
        (``ComputationGraph.scoreExamples``), one jitted vmap."""
        mds = self._to_mds(ds)
        dtype = self.conf.global_conf.jnp_dtype()
        inputs = {n: _as_jnp(f, dtype)
                  for n, f in zip(self.conf.inputs, mds.features)}
        labels = [_as_jnp(l, dtype) for l in mds.labels]

        def one(ins, labs):
            loss, _ = self._loss_fn(
                self.params, self.states,
                {k: v[None] for k, v in ins.items()},
                [l[None] for l in labs], None, None, None, train=False)
            return loss

        scores = jax.jit(jax.vmap(one))(inputs, labels)
        reg = self._regularization(self.params)
        scores = scores - reg + (reg if add_regularization else 0.0)
        return np.asarray(scores)

    # ------------------------------------------------------------------ misc
    def num_params(self) -> int:
        if self.params is None:
            return self.conf.num_params()
        return sum(v.size for p in self.params.values() for v in p.values())

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listeners(self, *listeners) -> None:
        self.listeners.extend(listeners)

    def clone(self) -> "ComputationGraph":
        # jnp.array COPIES the buffers: the original's donating train step
        # must not be able to invalidate the clone's arrays
        copy_arr = lambda a: jnp.array(a) if hasattr(a, "dtype") else a
        other = ComputationGraph(self.conf)
        other.params = jax.tree_util.tree_map(copy_arr, self.params)
        other.states = jax.tree_util.tree_map(copy_arr, self.states)
        other.updater_states = jax.tree_util.tree_map(copy_arr, self.updater_states)
        other._updaters = self._updaters
        other.iteration = self.iteration
        other.epoch = self.epoch
        other._rng_key = self._rng_key
        return other
