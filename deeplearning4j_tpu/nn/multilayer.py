"""MultiLayerNetwork — sequential model with DL4J's training API, TPU-native.

Reference: ``nn/multilayer/MultiLayerNetwork.java`` (3.5k LoC): ``init():549``
(flattened param buffer), ``fit(DataSetIterator):1262``, ``output:2006``,
``rnnTimeStep:2800``, ``evaluate:2979``, TBPTT dispatch ``:1309``.

TPU design: params are a pytree (list of per-layer dicts); the whole train
step — forward, loss, ``jax.grad`` backward, gradient normalization, l1/l2,
updater, param update — is ONE jitted function with donated buffers, so XLA
fuses it and params never leave HBM. There is no Solver/ConvexOptimizer object
tree; the optimizer loop IS the compiled function (the reference's
StochasticGradientDescent.optimize():58-98 collapses into it). TBPTT runs the
jitted chunk step in a host loop carrying stopped-gradient RNN state.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.constraints import apply_constraints
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer, check_carry_capacity
from deeplearning4j_tpu.nn.updaters import (
    Sgd,
    Updater,
    normalize_gradients,
    schedule_value,
)

Array = jax.Array
Params = List[Dict[str, Array]]
States = List[Dict[str, Array]]


def _as_jnp(x, dtype=None):
    if isinstance(x, (np.ndarray, list, tuple)) or np.isscalar(x):
        x = jnp.asarray(x)
    if dtype is not None and x.dtype != dtype and jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(dtype)
    return x


class MultiLayerNetwork:
    """Sequential network over a MultiLayerConfiguration."""

    # set by parallel.sharding.shard_model_with_rules: when present, fit()/
    # output() place incoming batches over the mesh's data axis so pjit sees
    # a consistent DP x MP layout end to end (GSPMD handles the rest), and
    # the train step pins updated params/opt-state back to the placed specs
    _mesh = None
    _param_shardings = None
    _upd_shardings = None

    def _pin_placements(self, new_params, new_upd):
        """Inside-jit: constrain step outputs to the rule-placed shardings.
        Without this GSPMD may emit one param with a sharding of its own
        choosing and every subsequent compile re-layouts around the drifted
        leaf (observed: a replicated positional table coming back
        model-sharded cost 18 forward all-gathers)."""
        if self._param_shardings is not None:
            new_params = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_params,
                self._param_shardings)
        if self._upd_shardings is not None and new_upd is not None:
            new_upd = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_upd,
                self._upd_shardings)
        return new_params, new_upd

    def __init__(self, conf: MultiLayerConfiguration):
        conf.finalize()
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self.params: Optional[Params] = None
        self.states: Optional[States] = None
        self.updater_states: Optional[List[Dict[str, Dict[str, Array]]]] = None
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._score_arr = None  # device array; float() only on read (no sync/step)
        self._rng_key: Optional[jax.Array] = None
        self._jit_cache: Dict[Any, Any] = {}
        self._rnn_carries: Optional[List[Any]] = None
        self._rnn_pos = 0
        # cumulative host→device batch payload shipped by fit(); the
        # TraceListener exports deltas as training_transfer_bytes_total
        self.transfer_bytes = 0
        # resolve per-layer / per-param updaters once
        self._updaters: List[Dict[str, Updater]] = []

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        g = self.conf.global_conf
        key = jax.random.PRNGKey(g.seed if seed is None else seed)
        self._rng_key = jax.random.fold_in(key, 0x5EED)
        dtype = g.jnp_dtype()
        keys = jax.random.split(key, len(self.layers))
        self.params = [l.init_params(k, dtype) for l, k in zip(self.layers, keys)]
        self.states = [l.init_state() for l in self.layers]
        default_updater = g.updater or Sgd(0.1)
        self._updaters = []
        self.updater_states = []
        for l, p in zip(self.layers, self.params):
            layer_upd = l.updater or default_updater
            bias_upd = l.bias_updater or g.bias_updater or layer_upd
            umap, smap = {}, {}
            for n, v in p.items():
                u = bias_upd if n == "b" else layer_upd
                umap[n] = u
                smap[n] = u.init_state(v)
            self._updaters.append(umap)
            self.updater_states.append(smap)
        self.iteration = 0
        self.epoch = 0
        return self

    @property
    def score_(self) -> float:
        """Last minibatch loss. Reading this syncs with the device; the train
        loop itself never blocks on it (PerformanceListener-friendly)."""
        return float("nan") if self._score_arr is None else float(self._score_arr)

    @score_.setter
    def score_(self, v) -> None:
        self._score_arr = v

    def _next_rng(self) -> jax.Array:
        self._rng_key, k = jax.random.split(self._rng_key)
        return k

    def _device_tick(self):
        from deeplearning4j_tpu.nn.tick import device_tick
        return device_tick(self)

    def _store_tick(self, new_it, new_rng) -> None:
        from deeplearning4j_tpu.nn.tick import store_tick
        store_tick(self, new_it, new_rng)

    # ------------------------------------------------------------- forward
    def _forward_all(self, params: Params, states: States, x: Array, *,
                     train: bool, rng: Optional[jax.Array], mask: Optional[Array],
                     carries: Optional[List[Any]] = None, upto: Optional[int] = None,
                     ) -> Tuple[Array, States, Optional[List[Any]]]:
        """Run layers [0, upto); returns (activation, new_states, new_carries)."""
        n_layers = len(self.layers) if upto is None else upto
        cd = self.conf.global_conf.jnp_compute_dtype()
        if cd is not None:
            # mixed precision: cast f32 master params + input to the compute
            # dtype; jax.grad through the cast yields master-dtype gradients
            cast = lambda a: (a.astype(cd)
                              if hasattr(a, "dtype")
                              and jnp.issubdtype(a.dtype, jnp.floating) else a)
            params = jax.tree_util.tree_map(cast, params)
            x = cast(x)
        h = x
        new_states: States = []
        new_carries: List[Any] = []
        rngs = (jax.random.split(rng, len(self.layers)) if rng is not None
                else [None] * len(self.layers))
        cur_mask = mask
        for i in range(len(self.layers)):
            if i >= n_layers:
                new_states.append(states[i])
                new_carries.append(None if carries is None else carries[i])
                continue
            layer = self.layers[i]
            if i in self.conf.preprocessors:
                h = self.conf.preprocessors[i](h)
            p_i, rng_i = params[i], rngs[i]
            if (getattr(layer, "weight_noise", None) is not None and train
                    and rng_i is not None):
                # IWeightNoise (DropConnect/WeightNoise): noise the WEIGHTS
                # at forward time, train only (weightnoise/DropConnect.java:19)
                rng_wn, rng_i = jax.random.split(rng_i)
                p_i = layer.weight_noise.apply(layer, p_i, rng_wn, train)
            if carries is not None and isinstance(layer, BaseRecurrentLayer):
                y, c = layer.forward_seq(p_i, h, carry=carries[i], mask=cur_mask,
                                         train=train, rng=rng_i)
                new_states.append(states[i])
                new_carries.append(c)
                h = y
            else:
                fwd = lambda p, hh, _l=layer, _i=i, _r=rng_i: _l.forward(
                    p, hh, state=states[_i], train=train, rng=_r,
                    mask=cur_mask)
                if train and self.conf.global_conf.gradient_checkpointing:
                    # rematerialize this layer's activations in the backward
                    # pass instead of storing them (HBM ↔ FLOPs trade)
                    fwd = jax.checkpoint(fwd)
                h, st = fwd(p_i, h)
                new_states.append(st if st else states[i])
                new_carries.append(None)
            # per-TIMESTEP masks collapse when the time dimension disappears;
            # a per-example [N]/[N,1] mask stays valid on 2d activations
            if (cur_mask is not None and h.ndim == 2 and cur_mask.ndim == 2
                    and cur_mask.shape[1] > 1):
                cur_mask = None
        return h, new_states, new_carries

    def _regularization(self, params: Params) -> Array:
        reg = jnp.asarray(0.0, jnp.float32)
        for l, p in zip(self.layers, params):
            for n, v in p.items():
                is_bias = n == "b"
                l1 = (l.l1_bias if is_bias else l.l1) or 0.0
                l2 = (l.l2_bias if is_bias else l.l2) or 0.0
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(v))
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(v * v)
        return reg

    def _loss_fn(self, params: Params, states: States, x, y, rng,
                 mask, label_mask, train: bool,
                 carries: Optional[List[Any]] = None):
        out_layer = self.layers[-1]
        if not out_layer.has_loss():
            raise ValueError("Last layer must be an output/loss layer for fit()")
        h, new_states, new_carries = self._forward_all(
            params, states, x, train=train, rng=rng, mask=mask, carries=carries,
            upto=len(self.layers) - 1)
        if (len(self.layers) - 1) in self.conf.preprocessors:
            h = self.conf.preprocessors[len(self.layers) - 1](h)
        if self.conf.global_conf.compute_dtype is not None:
            # loss head in f32 for stable softmax/log under mixed precision
            h = h.astype(jnp.float32)
        if label_mask is not None:
            lm = label_mask
        elif mask is None:
            lm = None
        elif h.ndim == 3:
            lm = mask
        elif mask.ndim == 1 or (mask.ndim == 2 and mask.shape[-1] == 1):
            # per-example feature mask masks the score too (DL4J ScoreUtil)
            lm = mask.reshape(mask.shape[0])
        else:
            lm = None
        p_out = params[-1]
        if (getattr(out_layer, "weight_noise", None) is not None and train
                and rng is not None):
            # output layers get weight noise too (DL4J noises every layer's
            # preOutput); fold_in keeps the key distinct from _forward_all's
            p_out = out_layer.weight_noise.apply(
                out_layer, p_out, jax.random.fold_in(rng, len(self.layers)),
                train)
        loss = out_layer.compute_loss(p_out, h, y, mask=lm)
        loss = loss + self._regularization(params)
        return loss, (new_states, new_carries)

    # ------------------------------------------------------------ train step
    def _apply_updates(self, params, grads, upd_states, it, ep):
        # "updater" helper seam: a registered fused kernel (e.g.
        # PallasUpdaterHelper) takes the whole per-param read-modify-write;
        # consulted at trace time, versioned into the train-step cache key
        from deeplearning4j_tpu.nn import helpers as _helpers
        uhelper = _helpers.get_helper("updater")
        new_params, new_upd = [], []
        for i, l in enumerate(self.layers):
            g_layer = grads[i]
            if l.gradient_normalization:
                g_layer = normalize_gradients(g_layer, l.gradient_normalization,
                                              l.gradient_normalization_threshold)
            p_new, s_new = {}, {}
            for n, g in g_layer.items():
                u = self._updaters[i][n]
                lr = u.lr_at(it, ep)
                t = it + 1.0  # 1-based step count for Adam-family bias correction
                if uhelper is not None and uhelper.supports(u, params[i][n], g):
                    p_new[n], s_new[n] = uhelper.apply(
                        u, params[i][n], g, upd_states[i][n], lr, t)
                    continue
                upd, s = u.update(g, upd_states[i][n], lr, t)
                p_new[n] = params[i][n] - upd.astype(params[i][n].dtype)
                s_new[n] = s
            # post-update parameter constraints (BaseConstraint.applyConstraint
            # runs after each iteration in the reference) — fused into the
            # jitted step, not a separate host pass
            p_new = apply_constraints(l, p_new)
            new_params.append(p_new)
            new_upd.append(s_new)
        return new_params, new_upd

    def _build_train_step(self, tbptt: bool):
        def step(params, states, upd_states, it, ep, x, y, mask, label_mask, rng, carries):
            # split on DEVICE and return the next key + iteration: the fit
            # loop then re-feeds them without any per-step host-side device
            # ops (a host rng split + two scalar placements cost ~14 ms/step
            # through a remote dispatch link — measured round 3)
            rng_use, rng_next = jax.random.split(rng)

            def lf(p):
                return self._loss_fn(p, states, x, y, rng_use, mask, label_mask,
                                     train=True, carries=carries if tbptt else None)
            from deeplearning4j_tpu.nn.tick import schedule_tick
            with schedule_tick(it, ep):  # dropout pSchedule sees the device tick
                (loss, (new_states, new_carries)), grads = jax.value_and_grad(lf, has_aux=True)(params)
            new_params, new_upd = self._apply_updates(params, grads, upd_states, it, ep)
            new_params, new_upd = self._pin_placements(new_params, new_upd)
            if tbptt:
                new_carries = jax.tree_util.tree_map(jax.lax.stop_gradient, new_carries)
            return new_params, new_states, new_upd, loss, new_carries, it + 1.0, rng_next

        return jax.jit(step, donate_argnums=(0, 1, 2, 3, 9))

    def _get_train_step(self, tbptt: bool):
        key = ("train", tbptt)
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = key + (_helpers.version(),)
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())
            self._jit_cache[key] = self._build_train_step(tbptt)
        return self._jit_cache[key]

    def _evict_stale(self, current_version: int) -> None:
        from deeplearning4j_tpu.nn import helpers as _helpers
        _helpers.evict_stale_jit_entries(self._jit_cache, current_version)

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            features_mask=None, labels_mask=None,
            prefetch_depth: Optional[int] = None) -> "MultiLayerNetwork":
        """Train. ``data`` is (x, y) arrays, a DataSet, or a DataSetIterator.

        Iterator sources are auto-wrapped in async host→device prefetch
        (``AsyncDataSetIterator`` + device-put stage): a producer thread
        prepares and ships batch N+1 while the device runs batch N, so the
        step never stalls on ETL or the transfer. ``prefetch_depth`` sets
        the queue depth (default 2 — double buffering); 0 disables.
        Iterators with ``async_supported = False`` (AsyncShield) are never
        wrapped. The per-batch wait shows up as a ``host_wait`` trace span
        and the shipped payload as ``training_transfer_bytes_total``."""
        if self.params is None:
            self.init()
        from deeplearning4j_tpu.datasets.dataset import (DataSet,  # no cycle
                                                         batch_nbytes)
        from deeplearning4j_tpu.datasets.iterators import wrap_for_prefetch
        from deeplearning4j_tpu.observe import trace as _trace

        if labels is not None:
            iterator = [DataSet(data, labels, features_mask, labels_mask)]
        elif isinstance(data, DataSet):
            iterator = [data]
        else:
            iterator = data  # assume iterable of DataSet
        iterator = wrap_for_prefetch(iterator, prefetch_depth)

        for ep in range(epochs):
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_start"):
                    listener.on_epoch_start(self)
            epoch_iter = iterator
            if hasattr(epoch_iter, "reset"):
                epoch_iter.reset()
            batches = iter(epoch_iter)
            while True:
                # host_wait = time the training thread blocks on the input
                # pipeline; ~zero when prefetch keeps the queue warm
                with _trace.span("host_wait", category="train"):
                    ds = next(batches, None)
                if ds is None:
                    break
                self.transfer_bytes += batch_nbytes(ds)
                self._fit_batch(ds)
            self.epoch += 1
            for listener in self.listeners:
                if hasattr(listener, "on_epoch_end"):
                    listener.on_epoch_end(self)
        return self

    def _get_multi_train_step(self):
        """K train steps as ONE compiled ``lax.scan`` over stacked batches
        (ComputationGraph._get_multi_train_step counterpart — see
        :meth:`fit_batches_on_device`)."""
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("train_scan", _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def multi(params, states, upd_states, it0, ep, xs, ys, rng0):
                def body(carry, batch):
                    params, states, upd, it, rng = carry
                    x, y = batch
                    rng, sub = jax.random.split(rng)
                    def lf(p):
                        return self._loss_fn(p, states, x, y, sub, None, None,
                                             train=True)
                    from deeplearning4j_tpu.nn.tick import schedule_tick
                    with schedule_tick(it, ep):
                        (loss, (new_states, _)), grads = jax.value_and_grad(
                            lf, has_aux=True)(params)
                    new_params, new_upd = self._apply_updates(
                        params, grads, upd, it, ep)
                    new_params, new_upd = self._pin_placements(new_params,
                                                               new_upd)
                    return (new_params, new_states, new_upd, it + 1.0, rng), loss

                (params, states, upd, _, _), losses = jax.lax.scan(
                    body, (params, states, upd_states, it0, rng0), (xs, ys))
                return params, states, upd, losses

            self._jit_cache[key] = jax.jit(multi, donate_argnums=(0, 1, 2))
        return self._jit_cache[key]

    def fit_batches_on_device(self, datasets) -> "MultiLayerNetwork":
        """Train on a window of equal-shape batches in ONE device dispatch
        (``lax.scan`` over the stacked window) — semantically identical to
        ``fit`` per batch; built for dispatch-bound setups on directly-
        attached hardware (tunneled backends that stream operands lazily
        can be SLOWER this way). Requires uniform shapes, no masks,
        standard backprop."""
        from deeplearning4j_tpu.nn.conf.network import normalize_backprop_type
        if self.params is None:
            self.init()
        if normalize_backprop_type(self.conf.backprop_type) != "standard":
            raise ValueError("fit_batches_on_device supports standard "
                             "backprop only (not TBPTT)")
        datasets = list(datasets)
        if not datasets:
            return self
        if any(ds.features_mask is not None or ds.labels_mask is not None
               for ds in datasets):
            raise ValueError("fit_batches_on_device does not carry masks")
        dtype = self.conf.global_conf.jnp_dtype()
        xs = jnp.stack([_as_jnp(ds.features, dtype) for ds in datasets])
        ys = jnp.stack([_as_jnp(ds.labels, dtype) for ds in datasets])
        multi = self._get_multi_train_step()
        it0 = jnp.asarray(self.iteration, jnp.float32)
        ep = jnp.asarray(self.epoch, jnp.float32)
        (self.params, self.states, self.updater_states, losses) = multi(
            self.params, self.states, self.updater_states, it0, ep, xs, ys,
            self._next_rng())
        self.last_batch_size = int(xs.shape[1])
        for i in range(len(datasets)):
            self._score_arr = losses[i]
            self.iteration += 1
            for listener in self.listeners:
                if hasattr(listener, "iteration_done"):
                    listener.iteration_done(self, self.iteration, self.epoch)
        return self

    def _fit_batch(self, ds) -> None:
        dtype = self.conf.global_conf.jnp_dtype()
        x = _as_jnp(ds.features, dtype)
        y = _as_jnp(ds.labels, dtype)
        mask = None if ds.features_mask is None else _as_jnp(ds.features_mask)
        lmask = None if ds.labels_mask is None else _as_jnp(ds.labels_mask)
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import place_batch
            x = place_batch(x, self._mesh)
            y = place_batch(y, self._mesh)
            mask = place_batch(mask, self._mesh)
            lmask = place_batch(lmask, self._mesh)

        from deeplearning4j_tpu.nn.conf.network import normalize_backprop_type
        if (normalize_backprop_type(self.conf.backprop_type) == "truncated_bptt"
                and x.ndim == 3):
            self._fit_tbptt(x, y, mask, lmask)
            return

        step = self._get_train_step(False)
        it, ep, rng = self._device_tick()
        (self.params, self.states, self.updater_states, loss, _,
         new_it, new_rng) = step(
            self.params, self.states, self.updater_states, it, ep,
            x, y, mask, lmask, rng, None)
        self._score_arr = loss
        self.last_batch_size = int(x.shape[0])
        self.iteration += 1
        self._store_tick(new_it, new_rng)
        for listener in self.listeners:
            if hasattr(listener, "iteration_done"):
                listener.iteration_done(self, self.iteration, self.epoch)

    def _fit_tbptt(self, x, y, mask, lmask) -> None:
        """Truncated BPTT (MultiLayerNetwork.doTruncatedBPTT:1309 parity):
        process the sequence in chunks of tbptt_fwd_length, carrying RNN state
        (stop-gradient) between chunks."""
        t_total = x.shape[1]
        # the chunk steps are jitted, where a finite carry (KV cache,
        # positional offset) cannot raise on overflow — reject here instead
        check_carry_capacity(
            ((f"layer {i} ({type(l).__name__})", l)
             for i, l in enumerate(self.layers)), t_total, "TBPTT")
        length = self.conf.tbptt_fwd_length
        n_chunks = max(1, math.ceil(t_total / length))
        batch = x.shape[0]
        self.last_batch_size = int(batch)
        dtype = x.dtype
        carries = [l.init_carry(batch, dtype) if isinstance(l, BaseRecurrentLayer) else None
                   for l in self.layers]
        for c in range(n_chunks):
            s, e = c * length, min((c + 1) * length, t_total)
            xc = x[:, s:e]
            yc = y[:, s:e] if y.ndim == 3 else y
            mc = None if mask is None else mask[:, s:e]
            lc = None if lmask is None else lmask[:, s:e]
            step = self._get_train_step(True)
            it, ep, rng = self._device_tick()
            (self.params, self.states, self.updater_states, loss, carries,
             new_it, new_rng) = step(
                self.params, self.states, self.updater_states, it, ep,
                xc, yc, mc, lc, rng, carries)
            self._score_arr = loss
            self.iteration += 1
            self._store_tick(new_it, new_rng)
        for listener in self.listeners:
            if hasattr(listener, "iteration_done"):
                listener.iteration_done(self, self.iteration, self.epoch)

    # ------------------------------------------------------------- inference
    def _output_fn(self):
        # one jitted callable; jax.jit itself specializes per input shape.
        # The helper-registry version is part of the key: the registry is
        # consulted at trace time, so registration changes must retrace.
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("out", _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def out_fn(params, states, x, mask):
                h, _, _ = self._forward_all(params, states, x, train=False,
                                            rng=None, mask=mask)
                return h
            self._jit_cache[key] = jax.jit(out_fn)
        return self._jit_cache[key]

    def output(self, x, mask=None) -> Array:
        """Inference forward. Also accepts a DataSetIterator (the
        reference's ``output(DataSetIterator)`` overload) — batch outputs
        are concatenated."""
        if hasattr(x, "features") or (hasattr(x, "__iter__")
                                      and not hasattr(x, "shape")
                                      and not isinstance(x, (list, tuple))):
            it = [x] if hasattr(x, "features") else x
            if hasattr(it, "reset"):
                it.reset()
            outs = [np.asarray(self.output(
                ds.features,
                mask=None if ds.features_mask is None else ds.features_mask))
                for ds in it]
            return jnp.concatenate([jnp.asarray(o) for o in outs], axis=0)
        dtype = self.conf.global_conf.jnp_dtype()
        x = _as_jnp(x, dtype)
        mask = None if mask is None else _as_jnp(mask)
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import place_batch
            x = place_batch(x, self._mesh)
            mask = place_batch(mask, self._mesh)
        return self._output_fn()(self.params, self.states, x, mask)

    def feed_forward(self, x, train: bool = False) -> List[Array]:
        """Per-layer activations (MultiLayerNetwork.feedForward parity)."""
        return self.feed_forward_to_layer(len(self.layers) - 1, x,
                                          train=train)

    def feed_forward_to_layer(self, layer_num: int, x,
                              train: bool = False) -> List[Array]:
        """Activations through layer ``layer_num`` inclusive, stopping
        there (``feedForwardToLayer:949``)."""
        if not 0 <= layer_num < len(self.layers):
            raise ValueError(f"layer_num {layer_num} out of range "
                             f"[0, {len(self.layers)})")
        dtype = self.conf.global_conf.jnp_dtype()
        h = _as_jnp(x, dtype)
        acts = [h]
        for i in range(layer_num + 1):
            if i in self.conf.preprocessors:
                h = self.conf.preprocessors[i](h)
            h, _ = self.layers[i].forward(self.params[i], h,
                                          state=self.states[i],
                                          train=train, rng=None)
            acts.append(h)
        return acts

    # -- layer / parameter access (MultiLayerNetwork getters) ---------------
    @property
    def n_layers(self) -> int:
        """``getnLayers()``."""
        return len(self.layers)

    def get_layer(self, idx) -> Layer:
        """Layer by index or by name (``getLayer``)."""
        if isinstance(idx, str):
            for l in self.layers:
                if l.name == idx:
                    return l
            raise KeyError(f"no layer named {idx!r}")
        return self.layers[idx]

    def get_layers(self) -> List[Layer]:
        return list(self.layers)

    def get_output_layer(self) -> Layer:
        """``getOutputLayer()`` — the final layer."""
        return self.layers[-1]

    def param_table(self) -> Dict[str, Array]:
        """All parameters keyed DL4J-style ``"<layerIdx>_<name>"``
        (``paramTable()``), e.g. ``"0_W"``."""
        out: Dict[str, Array] = {}
        for i, p in enumerate(self.params or []):
            for name, arr in p.items():
                out[f"{i}_{name}"] = arr
        return out

    def get_param(self, key: str) -> Array:
        """One parameter by ``"<layerIdx>_<name>"`` key (``getParam``)."""
        idx, name = key.split("_", 1)
        return self.params[int(idx)][name]

    def set_param(self, key: str, value) -> None:
        """Replace one parameter (``setParam``); shape must match."""
        idx, name = key.split("_", 1)
        i = int(idx)
        old = self.params[i][name]
        arr = jnp.asarray(value, old.dtype)
        if arr.shape != old.shape:
            raise ValueError(
                f"shape mismatch for {key}: {arr.shape} vs {old.shape}")
        self.params[i] = {**self.params[i], name: arr}

    def num_labels(self) -> int:
        """Output dimension of the final layer (``numLabels``)."""
        out = getattr(self.layers[-1], "n_out", None)
        if not out:
            raise ValueError("output layer has no n_out")
        return int(out)

    # -- convenience classifier metrics -------------------------------------
    def f1_score(self, features, labels) -> float:
        """Macro F1 on a batch (``f1Score``)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation()
        e.eval(np.asarray(labels), np.asarray(self.output(features)))
        return float(e.f1())

    def label_probabilities(self, x) -> np.ndarray:
        """Per-class probabilities (``labelProbabilities``) — the output
        activations for a softmax/sigmoid head."""
        return np.asarray(self.output(x))

    # -- rnn stored-state access --------------------------------------------
    def rnn_get_previous_state(self, layer: int):
        """Stored carry of a recurrent layer (``rnnGetPreviousState``),
        or None before any ``rnn_time_step`` call."""
        if self._rnn_carries is None:
            return None
        return self._rnn_carries[layer]

    def rnn_set_previous_state(self, layer: int, state,
                               position: Optional[int] = None) -> None:
        """Overwrite a recurrent layer's stored carry
        (``rnnSetPreviousState``); requires a prior ``rnn_time_step`` so
        the carry list exists.

        ``position``: total timesteps already absorbed by ``state``.
        Mandatory when any layer has a finite carry (KV cache) — the
        host-side capacity guard tracks position separately from the
        opaque carry, and a restored cache whose write offset disagrees
        with the guard would let a jitted ``dynamic_update_slice``
        silently clamp out-of-range writes."""
        if self._rnn_carries is None:
            raise ValueError(
                "no stored rnn state to overwrite; call rnn_time_step "
                "first to initialize the carries")
        if position is not None:
            self._rnn_pos = int(position)
        elif any(isinstance(l, BaseRecurrentLayer)
                 and l.carry_capacity() is not None for l in self.layers):
            raise ValueError(
                "rnn_set_previous_state needs position= when a layer has "
                "a finite carry capacity (KV cache): the restored cache's "
                "write offset must match the capacity guard")
        self._rnn_carries[layer] = state

    # -- save/load facades ----------------------------------------------------
    def save(self, path: str, save_updater: bool = True) -> None:
        """Write this model as a checkpoint zip (``MultiLayerNetwork.save``)."""
        from deeplearning4j_tpu.util import model_serializer
        model_serializer.write_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "MultiLayerNetwork":
        """Restore from a checkpoint zip (``MultiLayerNetwork.load``)."""
        from deeplearning4j_tpu.util import model_serializer
        return model_serializer.restore_multi_layer_network(
            path, load_updater=load_updater)

    def predict(self, x) -> np.ndarray:
        out = self.output(x)
        return np.asarray(jnp.argmax(out, axis=-1))

    def score(self, ds=None) -> float:
        if ds is None:
            return self.score_
        dtype = self.conf.global_conf.jnp_dtype()
        x = _as_jnp(ds.features, dtype)
        y = _as_jnp(ds.labels, dtype)
        mask = None if ds.features_mask is None else _as_jnp(ds.features_mask)
        lmask = None if ds.labels_mask is None else _as_jnp(ds.labels_mask)
        loss, _ = self._loss_fn(self.params, self.states, x, y, None, mask, lmask,
                                train=False)
        return float(loss)

    def score_examples(self, ds, add_regularization: bool = False) -> np.ndarray:
        """Per-example losses (``MultiLayerNetwork.scoreExamples``): the
        data term of each example's loss, computed in one jitted ``vmap``
        over single-example batches (inference statistics, so examples are
        independent); ``add_regularization`` adds the network's l1/l2 term
        to every score, matching the reference."""
        dtype = self.conf.global_conf.jnp_dtype()
        x = _as_jnp(ds.features, dtype)
        y = _as_jnp(ds.labels, dtype)
        lmask = None if ds.labels_mask is None else _as_jnp(ds.labels_mask)

        def one(xi, yi, lmi):
            loss, _ = self._loss_fn(self.params, self.states, xi[None],
                                    yi[None], None, None,
                                    None if lmi is None else lmi[None],
                                    train=False)
            return loss

        if lmask is None:
            scores = jax.jit(jax.vmap(lambda a, b: one(a, b, None)))(x, y)
        else:
            scores = jax.jit(jax.vmap(one))(x, y, lmask)
        reg = self._regularization(self.params)
        # _loss_fn includes the regularization term once per (1-example)
        # batch; scoreExamples semantics: data term per example, plus reg
        # only when requested
        scores = scores - reg + (reg if add_regularization else 0.0)
        return np.asarray(scores)

    def compute_gradient_and_score(self, x, y, features_mask=None, labels_mask=None):
        """Returns (gradients pytree, score) without updating params —
        the hook used by gradient checks (GradientCheckUtil parity)."""
        dtype = self.conf.global_conf.jnp_dtype()
        x = _as_jnp(x, dtype)
        y = _as_jnp(y, dtype)

        def lf(p):
            return self._loss_fn(p, self.states, x, y, None,
                                 features_mask, labels_mask, train=False)

        (loss, _), grads = jax.value_and_grad(lf, has_aux=True)(self.params)
        return grads, float(loss)

    # ------------------------------------------------------ stateful RNN API
    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None
        self._rnn_pos = 0

    def _rnn_step_fn(self):
        """Jitted stateful step (see ComputationGraph._rnn_step_fn): one
        executable per input shape for autoregressive decoding."""
        from deeplearning4j_tpu.nn import helpers as _helpers
        key = ("rnn_step", _helpers.version())
        if key not in self._jit_cache:
            self._evict_stale(_helpers.version())

            def step_fn(params, states, x, carries):
                h, _, new_carries = self._forward_all(
                    params, states, x, train=False, rng=None, mask=None,
                    carries=carries)
                return h, new_carries
            self._jit_cache[key] = jax.jit(step_fn)
        return self._jit_cache[key]

    def rnn_time_step(self, x) -> Array:
        """Stateful single/multi-step inference (rnnTimeStep:2800 parity).
        x: [N, T, C] (or [N, C] for one step)."""
        dtype = self.conf.global_conf.jnp_dtype()
        x = _as_jnp(x, dtype)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        if self._rnn_carries is None:
            batch = x.shape[0]
            self._rnn_pos = 0
            self._rnn_carries = [
                l.init_carry(batch, dtype) if isinstance(l, BaseRecurrentLayer) else None
                for l in self.layers]
        # host-side capacity guard: finite carries cannot raise under jit
        t_new = x.shape[1]
        check_carry_capacity(
            ((f"layer {i}", l) for i, l in enumerate(self.layers)),
            self._rnn_pos + t_new,
            f"rnn_time_step at position {self._rnn_pos}+{t_new}")
        h, self._rnn_carries = self._rnn_step_fn()(
            self.params, self.states, x, self._rnn_carries)
        self._rnn_pos += t_new
        return h[:, -1, :] if squeeze and h.ndim == 3 else h

    # ------------------------------------------------------------ evaluation
    # ------------------------------------------------------------- pretrain
    def pretrain_layer(self, layer_idx: int, data, epochs: int = 1
                       ) -> "MultiLayerNetwork":
        """Unsupervised pretraining of ONE layer
        (``MultiLayerNetwork.pretrainLayer``): inputs are featurized
        through the frozen layers below, then the layer's own
        ``pretrain_loss`` (VAE ELBO / autoencoder reconstruction) is
        minimized with its configured updater in a jitted step."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if self.params is None:
            self.init()
        layer = self.layers[layer_idx]
        if not hasattr(layer, "pretrain_loss"):
            raise ValueError(
                f"layer {layer_idx} ({type(layer).__name__}) has no "
                "pretrain_loss — only VAE/autoencoder layers pretrain")
        if hasattr(data, "features"):
            iterator = [data]
        elif isinstance(data, np.ndarray) or hasattr(data, "shape"):
            iterator = [DataSet(data, data)]
        else:
            iterator = data
        dtype = self.conf.global_conf.jnp_dtype()

        def step(p_i, upd_i, it, x, rng):
            loss, grads = jax.value_and_grad(
                lambda p: layer.pretrain_loss(p, x, rng))(p_i)
            new_p, new_upd = {}, {}
            for n, g in grads.items():
                u = self._updaters[layer_idx][n]
                lr = u.lr_at(it, 0.0)
                delta, s = u.update(g, upd_i[n], lr, it + 1.0)
                new_p[n] = p_i[n] - delta.astype(p_i[n].dtype)
                new_upd[n] = s
            return new_p, new_upd, loss

        jstep = jax.jit(step, donate_argnums=(0, 1))
        it_count = 0
        loss = None
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                x = _as_jnp(ds.features, dtype)
                h, _, _ = self._forward_all(
                    self.params, self.states, x, train=False, rng=None,
                    mask=None, upto=layer_idx)
                (self.params[layer_idx], self.updater_states[layer_idx],
                 loss) = jstep(self.params[layer_idx],
                               self.updater_states[layer_idx],
                               jnp.asarray(float(it_count), jnp.float32),
                               h, self._next_rng())
                it_count += 1
        if loss is not None:
            self._score_arr = loss
        return self

    def pretrain(self, data, epochs: int = 1) -> "MultiLayerNetwork":
        """Layer-wise unsupervised pretraining over every pretrainable
        layer in order (``MultiLayerNetwork.pretrain(DataSetIterator)``)."""
        if self.params is None:
            self.init()
        for i, l in enumerate(self.layers):
            if hasattr(l, "pretrain_loss"):
                self.pretrain_layer(i, data, epochs=epochs)
        return self

    def evaluate(self, iterator, top_n: int = 1) -> "Evaluation":
        """Evaluate over an iterator (``MultiLayerNetwork.evaluate``).
        ``top_n`` > 1 additionally tracks top-N accuracy; when the iterator
        collects record metadata (``collect_meta_data=True``), per-record
        predictions are recorded for error drilldown (``doEvaluation``
        passes ``getExampleMetaData`` through, MultiLayerNetwork.java)."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation(top_n=top_n)
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features, mask=None if ds.features_mask is None
                              else _as_jnp(ds.features_mask))
            e.eval(np.asarray(ds.labels), np.asarray(out),
                   mask=None if ds.labels_mask is None else np.asarray(ds.labels_mask),
                   record_meta_data=getattr(ds, "example_meta_data", None))
        return e

    def evaluate_roc(self, iterator, threshold_steps: int = 0) -> "ROC":
        """Binary ROC over an iterator (``MultiLayerNetwork.evaluateROC
        :2999``); ``threshold_steps > 0`` uses the binned mergeable mode."""
        from deeplearning4j_tpu.eval.roc import ROC
        r = ROC(threshold_steps=threshold_steps)
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features,
                              mask=None if ds.features_mask is None
                              else _as_jnp(ds.features_mask))
            r.eval(np.asarray(ds.labels), np.asarray(out),
                   mask=None if ds.labels_mask is None
                   else np.asarray(ds.labels_mask))
        return r

    def evaluate_roc_multi_class(self, iterator,
                                 threshold_steps: int = 0
                                 ) -> "ROCMultiClass":
        """One-vs-all ROC per class (``evaluateROCMultiClass``)."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        r = ROCMultiClass(threshold_steps=threshold_steps)
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features,
                              mask=None if ds.features_mask is None
                              else _as_jnp(ds.features_mask))
            r.eval(np.asarray(ds.labels), np.asarray(out),
                   mask=None if ds.labels_mask is None
                   else np.asarray(ds.labels_mask))
        return r

    def evaluate_roc_binary(self, iterator,
                            threshold_steps: int = 0) -> "ROCBinary":
        """Per-output binary ROC for multi-label heads
        (``doEvaluation`` with ROCBinary), masks honored."""
        from deeplearning4j_tpu.eval.roc import ROCBinary
        r = ROCBinary(threshold_steps=threshold_steps)
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features,
                              mask=None if ds.features_mask is None
                              else _as_jnp(ds.features_mask))
            r.eval(np.asarray(ds.labels), np.asarray(out),
                   mask=None if ds.labels_mask is None
                   else np.asarray(ds.labels_mask))
        return r

    def evaluate_regression(self, iterator) -> "RegressionEvaluation":
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        e = RegressionEvaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features)
            e.eval(np.asarray(ds.labels), np.asarray(out))
        return e

    # -------------------------------------------------------------- listeners
    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listeners(self, *listeners) -> None:
        self.listeners.extend(listeners)

    def summary(self) -> str:
        """Layer table with parameter counts
        (``MultiLayerNetwork.summary()``)."""
        if self.params is None:
            self.init()
        rows = []
        total = 0
        for i, (l, p) in enumerate(zip(self.layers, self.params)):
            n = sum(int(np.prod(v.shape)) for v in p.values())
            total += n
            shapes = ", ".join(f"{k}{tuple(v.shape)}"
                               for k, v in sorted(p.items()))
            name = getattr(l, "name", None) or ""
            rows.append((str(i), f"{type(l).__name__}"
                         + (f" ({name})" if name else ""),
                         f"{n:,}", shapes))
        w0 = max(5, max(len(r[0]) for r in rows))
        w1 = max(10, max(len(r[1]) for r in rows))
        w2 = max(8, max(len(r[2]) for r in rows))
        lines = ["=" * 76,
                 f"{'index':<{w0}}  {'layer':<{w1}}  {'params':>{w2}}  shapes",
                 "-" * 76]
        for r in rows:
            lines.append(f"{r[0]:<{w0}}  {r[1]:<{w1}}  {r[2]:>{w2}}  {r[3]}")
        lines += ["-" * 76, f"Total parameters: {total:,}", "=" * 76]
        return "\n".join(lines)

    def set_learning_rate(self, lr) -> None:
        """Override every updater's learning rate at runtime
        (``MultiLayerNetwork.setLearningRate``): updaters are frozen
        dataclasses closed over by the jitted step, so the override
        rebuilds them (state layouts are unchanged — momentum carries
        over) and invalidates the jit cache for a retrace."""
        import dataclasses as _dc
        rep = lambda u: (_dc.replace(u, learning_rate=lr)
                         if hasattr(u, "learning_rate") else u)
        self._updaters = [
            {n: rep(u) for n, u in umap.items()}
            for umap in self._updaters]
        for i, l in enumerate(self.layers):
            if l.updater is not None and hasattr(l.updater,
                                                 "learning_rate"):
                l.updater = _dc.replace(l.updater, learning_rate=lr)
        g = self.conf.global_conf
        if g.updater is not None and hasattr(g.updater, "learning_rate"):
            g.updater = _dc.replace(g.updater, learning_rate=lr)
        self._jit_cache.clear()

    def layer_size(self, layer_idx: int) -> int:
        """``layerSize(int)``: the layer's output size (nOut)."""
        l = self.layers[layer_idx]
        n = getattr(l, "n_out", None)
        if n:
            return int(n)
        p = (self.params or [{}] * len(self.layers))[layer_idx]
        if "W" in p:
            return int(p["W"].shape[-1])
        raise ValueError(f"layer {layer_idx} has no defined output size")

    def get_layer_names(self) -> List[str]:
        """``getLayerNames``: per-layer names (class name when unnamed)."""
        return [getattr(l, "name", None) or type(l).__name__
                for l in self.layers]

    def to_computation_graph(self) -> "Any":
        """Convert to an equivalent single-chain ComputationGraph carrying
        the SAME parameters and states (``toComputationGraph``)."""
        import copy

        from deeplearning4j_tpu.nn.conf.graph_conf import (
            GraphBuilder, VertexDef)
        from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        names = []
        counts = {}
        for l in self.layers:
            base = getattr(l, "name", None) or type(l).__name__.lower()
            counts[base] = counts.get(base, 0) + 1
            names.append(base if counts[base] == 1 else
                         f"{base}_{counts[base]}")
        g = GraphBuilder(copy.deepcopy(self.conf.global_conf))
        g.add_inputs("input")
        prev = "input"
        for nm, l in zip(names, self.layers):
            g.add_layer(nm, copy.deepcopy(l), prev)
            prev = nm
        conf = g.set_outputs(prev).build()
        net = ComputationGraph(conf)
        if self.params is not None:
            net.init()
            net.params = {nm: dict(p) for nm, p in zip(names, self.params)}
            net.states = {nm: dict(s) for nm, s in zip(names, self.states)}
            net.updater_states = {nm: {k: dict(v) for k, v in u.items()}
                                  for nm, u in zip(names,
                                                   self.updater_states)}
            net.iteration = self.iteration
            net.epoch = self.epoch
        return net

    # ------------------------------------------------------------------ misc
    def num_params(self) -> int:
        if self.params is None:
            return self.conf.num_params()
        total = 0
        for p in self.params:
            for v in p.values():
                total += v.size
        return total

    def params_flat(self) -> np.ndarray:
        """Single flattened param vector (DL4J params() parity)."""
        leaves = []
        for p in self.params:
            for n in sorted(p):
                leaves.append(np.asarray(p[n]).ravel())
        return np.concatenate(leaves) if leaves else np.zeros(0)

    def set_params_flat(self, flat: np.ndarray) -> None:
        offset = 0
        new_params = []
        for p in self.params:
            d = {}
            for n in sorted(p):
                size = p[n].size
                d[n] = jnp.asarray(flat[offset:offset + size].reshape(p[n].shape),
                                   p[n].dtype)
                offset += size
            new_params.append(d)
        self.params = new_params

    def clone(self) -> "MultiLayerNetwork":
        # jnp.array COPIES the buffers: the original's donating train step
        # must not be able to invalidate the clone's arrays
        copy_arr = lambda a: jnp.array(a) if hasattr(a, "dtype") else a
        other = MultiLayerNetwork(self.conf)
        other.params = jax.tree_util.tree_map(copy_arr, self.params)
        other.states = jax.tree_util.tree_map(copy_arr, self.states)
        other.updater_states = jax.tree_util.tree_map(copy_arr, self.updater_states)
        other._updaters = self._updaters
        other.iteration = self.iteration
        other.epoch = self.epoch
        other._rng_key = self._rng_key
        return other
