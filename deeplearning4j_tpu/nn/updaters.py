"""Updaters (optimizers) and learning-rate schedules with ND4J semantics.

Reference: ND4J ``org.nd4j.linalg.learning.config`` (Sgd, Adam, AdaMax,
AdaDelta, AdaGrad, Nadam, Nesterovs, NoOp, RmsProp, AMSGrad) applied by DL4J's
``UpdaterBlock.update`` (``nn/updater/UpdaterBlock.java:105``). DL4J keeps
updater state in one flattened view array; here state is a pytree mirroring the
param pytree — functionally identical, and XLA fuses the elementwise update
chain into a single kernel either way.

Convention: ``apply_updater`` returns the *update to subtract* from params
(DL4J's step function performs ``params -= update``). Each updater is a frozen
dataclass (hashable → safe as a jit static argument); state is a dict of
arrays. The iteration/epoch counters arrive as traced scalars so jit never
recompiles across steps.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp

Array = jax.Array


# ---------------------------------------------------------------------------
# Schedules (ND4J ISchedule: Fixed/Exponential/Inverse/Poly/Sigmoid/Step/Map)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Schedule:
    """Base class; subclasses implement value(iteration, epoch)."""

    schedule_type: str = "iteration"  # "iteration" | "epoch"

    def _t(self, iteration, epoch):
        return epoch if self.schedule_type == "epoch" else iteration

    def value(self, iteration, epoch):  # pragma: no cover - abstract
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@schedule"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "Schedule":
        d = dict(d)
        cls = _SCHEDULES[d.pop("@schedule")]
        if cls is MapSchedule and "values" in d and isinstance(d["values"], dict):
            d["values"] = tuple(sorted((int(k), float(v)) for k, v in d["values"].items()))
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class FixedSchedule(Schedule):
    value_: float = 0.001

    def value(self, iteration, epoch):
        return self.value_


@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(Schedule):
    initial_value: float = 0.001
    gamma: float = 0.99

    def value(self, iteration, epoch):
        return self.initial_value * self.gamma ** self._t(iteration, epoch)


@dataclasses.dataclass(frozen=True)
class InverseSchedule(Schedule):
    initial_value: float = 0.001
    gamma: float = 0.99
    power: float = 1.0

    def value(self, iteration, epoch):
        return self.initial_value / (1.0 + self.gamma * self._t(iteration, epoch)) ** self.power


@dataclasses.dataclass(frozen=True)
class PolySchedule(Schedule):
    initial_value: float = 0.001
    power: float = 1.0
    max_iter: int = 10000

    def value(self, iteration, epoch):
        frac = jnp.minimum(self._t(iteration, epoch) / self.max_iter, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(Schedule):
    initial_value: float = 0.001
    gamma: float = 0.99
    step_size: int = 100

    def value(self, iteration, epoch):
        t = self._t(iteration, epoch)
        return self.initial_value / (1.0 + jnp.exp(-self.gamma * (t - self.step_size)))


@dataclasses.dataclass(frozen=True)
class StepSchedule(Schedule):
    initial_value: float = 0.001
    decay_rate: float = 0.1
    step: float = 100.0

    def value(self, iteration, epoch):
        t = self._t(iteration, epoch)
        return self.initial_value * self.decay_rate ** jnp.floor(t / self.step)


@dataclasses.dataclass(frozen=True)
class MapSchedule(Schedule):
    """Piecewise-constant schedule from {iteration_or_epoch: value}.

    ``values`` is a tuple of (threshold, value) pairs sorted by threshold;
    entry 0 must have threshold 0.
    """

    values: tuple = ((0, 0.001),)

    def value(self, iteration, epoch):
        t = self._t(iteration, epoch)
        out = jnp.asarray(self.values[0][1], jnp.float32)
        for thresh, val in self.values[1:]:
            out = jnp.where(t >= thresh, jnp.asarray(val, jnp.float32), out)
        return out


@dataclasses.dataclass(frozen=True)
class WarmupCosineSchedule(Schedule):
    """TPU-era extra (not in ND4J): linear warmup then cosine decay."""

    peak_value: float = 0.001
    warmup_steps: int = 100
    total_steps: int = 10000
    final_value: float = 0.0

    def value(self, iteration, epoch):
        t = jnp.asarray(self._t(iteration, epoch), jnp.float32)
        warm = self.peak_value * t / max(self.warmup_steps, 1)
        prog = jnp.clip((t - self.warmup_steps) / max(self.total_steps - self.warmup_steps, 1), 0.0, 1.0)
        cos = self.final_value + 0.5 * (self.peak_value - self.final_value) * (1 + jnp.cos(jnp.pi * prog))
        return jnp.where(t < self.warmup_steps, warm, cos)


_SCHEDULES = {
    c.__name__: c
    for c in [FixedSchedule, ExponentialSchedule, InverseSchedule, PolySchedule,
              SigmoidSchedule, StepSchedule, MapSchedule, WarmupCosineSchedule]
}


def schedule_value(lr: Union[float, Schedule], iteration, epoch):
    if isinstance(lr, Schedule):
        return lr.value(iteration, epoch)
    return lr


# ---------------------------------------------------------------------------
# Updaters
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Updater:
    """Base updater config. ``learning_rate`` may be a float or a Schedule."""

    learning_rate: Union[float, Schedule] = 0.001

    # -- state ------------------------------------------------------------
    def init_state(self, param: Array) -> Dict[str, Array]:
        return {}

    # -- update (returns value to SUBTRACT from param) --------------------
    def update(self, grad: Array, state: Dict[str, Array], lr, t):
        raise NotImplementedError

    def lr_at(self, iteration, epoch):
        return schedule_value(self.learning_rate, iteration, epoch)

    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = v.to_dict() if isinstance(v, Schedule) else v
        d["@updater"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "Updater":
        d = dict(d)
        cls = _UPDATERS[d.pop("@updater")]
        if isinstance(d.get("learning_rate"), dict):
            d["learning_rate"] = Schedule.from_dict(d["learning_rate"])
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class Sgd(Updater):
    learning_rate: Union[float, Schedule] = 0.1

    def update(self, grad, state, lr, t):
        return lr * grad, state


@dataclasses.dataclass(frozen=True)
class NoOp(Updater):
    learning_rate: Union[float, Schedule] = 0.0

    def update(self, grad, state, lr, t):
        return jnp.zeros_like(grad), state


@dataclasses.dataclass(frozen=True)
class Nesterovs(Updater):
    """Nesterov momentum, DL4J formulation (NesterovsUpdater):
    v' = mu*v - lr*g;  x += -mu*v + (1+mu)*v'  (we return the negation)."""

    learning_rate: Union[float, Schedule] = 0.1
    momentum: float = 0.9

    def init_state(self, param):
        return {"v": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        v_prev = state["v"]
        v = self.momentum * v_prev - lr * grad
        update = -(-self.momentum * v_prev + (1.0 + self.momentum) * v)
        return update, {"v": v}


@dataclasses.dataclass(frozen=True)
class Adam(Updater):
    learning_rate: Union[float, Schedule] = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        alpha = lr * jnp.sqrt(1 - self.beta2**t) / (1 - self.beta1**t)
        return alpha * m / (jnp.sqrt(v) + self.epsilon), {"m": m, "v": v}


@dataclasses.dataclass(frozen=True)
class AdaMax(Updater):
    learning_rate: Union[float, Schedule] = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "u": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        u = jnp.maximum(self.beta2 * state["u"], jnp.abs(grad))
        alpha = lr / (1 - self.beta1**t)
        return alpha * m / (u + self.epsilon), {"m": m, "u": u}


@dataclasses.dataclass(frozen=True)
class Nadam(Updater):
    learning_rate: Union[float, Schedule] = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        m_hat = m / (1 - self.beta1**t)
        v_hat = v / (1 - self.beta2**t)
        m_bar = self.beta1 * m_hat + (1 - self.beta1) * grad / (1 - self.beta1**t)
        return lr * m_bar / (jnp.sqrt(v_hat) + self.epsilon), {"m": m, "v": v}


@dataclasses.dataclass(frozen=True)
class AMSGrad(Updater):
    learning_rate: Union[float, Schedule] = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param),
                "v_hat": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        m = self.beta1 * state["m"] + (1 - self.beta1) * grad
        v = self.beta2 * state["v"] + (1 - self.beta2) * grad * grad
        v_hat = jnp.maximum(state["v_hat"], v)
        alpha = lr * jnp.sqrt(1 - self.beta2**t) / (1 - self.beta1**t)
        return alpha * m / (jnp.sqrt(v_hat) + self.epsilon), {"m": m, "v": v, "v_hat": v_hat}


@dataclasses.dataclass(frozen=True)
class AdaGrad(Updater):
    learning_rate: Union[float, Schedule] = 0.01
    epsilon: float = 1e-6

    def init_state(self, param):
        return {"h": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        h = state["h"] + grad * grad
        return lr * grad / (jnp.sqrt(h) + self.epsilon), {"h": h}


@dataclasses.dataclass(frozen=True)
class AdaDelta(Updater):
    """No learning rate — DL4J AdaDeltaUpdater semantics."""

    learning_rate: Union[float, Schedule] = 0.0  # unused
    rho: float = 0.95
    epsilon: float = 1e-6

    def init_state(self, param):
        return {"eg2": jnp.zeros_like(param), "edx2": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        eg2 = self.rho * state["eg2"] + (1 - self.rho) * grad * grad
        dx = grad * jnp.sqrt(state["edx2"] + self.epsilon) / jnp.sqrt(eg2 + self.epsilon)
        edx2 = self.rho * state["edx2"] + (1 - self.rho) * dx * dx
        return dx, {"eg2": eg2, "edx2": edx2}


@dataclasses.dataclass(frozen=True)
class RmsProp(Updater):
    learning_rate: Union[float, Schedule] = 0.001
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, param):
        return {"g2": jnp.zeros_like(param)}

    def update(self, grad, state, lr, t):
        g2 = self.rms_decay * state["g2"] + (1 - self.rms_decay) * grad * grad
        return lr * grad / jnp.sqrt(g2 + self.epsilon), {"g2": g2}


_UPDATERS = {
    c.__name__: c
    for c in [Sgd, NoOp, Nesterovs, Adam, AdaMax, Nadam, AMSGrad, AdaGrad,
              AdaDelta, RmsProp]
}


def resolve_updater(spec: Union[str, Updater, dict, None]) -> Updater:
    if spec is None:
        return Sgd()
    if isinstance(spec, Updater):
        return spec
    if isinstance(spec, dict):
        return Updater.from_dict(spec)
    key = spec.lower()
    aliases = {"sgd": Sgd, "adam": Adam, "adamax": AdaMax, "nadam": Nadam,
               "amsgrad": AMSGrad, "adagrad": AdaGrad, "adadelta": AdaDelta,
               "rmsprop": RmsProp, "nesterovs": Nesterovs, "noop": NoOp,
               "none": NoOp}
    if key not in aliases:
        raise ValueError(f"Unknown updater {spec!r}")
    return aliases[key]()


# ---------------------------------------------------------------------------
# Gradient normalization (DL4J GradientNormalization enum)
# ---------------------------------------------------------------------------

def normalize_gradients(grads: Dict[str, Array], mode: Optional[str],
                        threshold: float = 1.0) -> Dict[str, Array]:
    """Apply DL4J GradientNormalization to one layer's gradient dict.

    Modes: None | "renormalize_l2_per_layer" | "renormalize_l2_per_param_type"
    | "clip_elementwise_absolute_value" | "clip_l2_per_layer"
    | "clip_l2_per_param_type".
    """
    if not mode or mode == "none":
        return grads
    mode = mode.lower()
    if mode == "renormalize_l2_per_param_type":
        return {k: g / jnp.maximum(jnp.linalg.norm(g.ravel()), 1e-8) for k, g in grads.items()}
    if mode == "clip_elementwise_absolute_value":
        return {k: jnp.clip(g, -threshold, threshold) for k, g in grads.items()}
    if mode == "clip_l2_per_param_type":
        out = {}
        for k, g in grads.items():
            n = jnp.linalg.norm(g.ravel())
            out[k] = jnp.where(n > threshold, g * (threshold / jnp.maximum(n, 1e-8)), g)
        return out
    # layer-wide modes need the joint norm
    leaves = [g.ravel() for g in grads.values()]
    norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves))
    if mode == "renormalize_l2_per_layer":
        return {k: g / jnp.maximum(norm, 1e-8) for k, g in grads.items()}
    if mode == "clip_l2_per_layer":
        scale = jnp.where(norm > threshold, threshold / jnp.maximum(norm, 1e-8), 1.0)
        return {k: g * scale for k, g in grads.items()}
    raise ValueError(f"Unknown gradient normalization mode {mode!r}")
