"""Layer modules: each layer is a config dataclass carrying shape inference,
parameter initialization, and a pure functional forward pass.

Unlike DL4J's config/impl split (``nn/conf/layers/*`` vs ``nn/layers/*``)
there are no hand-written backprop pairs — ``jax.grad`` differentiates the
forward functions, and gradient-check tests (tests/test_gradients.py) keep the
math honest the same way DL4J's gradientcheck suites do.
"""

from deeplearning4j_tpu.nn.layers.base import Layer, LAYER_REGISTRY, layer_from_dict  # noqa: F401
from deeplearning4j_tpu.nn.layers.core import (  # noqa: F401
    DenseLayer,
    ActivationLayer,
    DropoutLayer,
    MaskLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
    PositionalEmbeddingLayer,
    ElementWiseMultiplicationLayer,
    PReLULayer,
)
from deeplearning4j_tpu.nn.layers.output import (  # noqa: F401
    OutputLayer,
    RnnOutputLayer,
    LossLayer,
    RnnLossLayer,
    CnnLossLayer,
    CenterLossOutputLayer,
)
from deeplearning4j_tpu.nn.layers.conv import (  # noqa: F401
    ConvolutionLayer,
    Convolution1DLayer,
    Deconvolution2DLayer,
    SeparableConvolution2DLayer,
    DepthwiseConvolution2DLayer,
    ZeroPaddingLayer,
    ZeroPadding1DLayer,
    CropLayer,
    SpaceToDepthLayer,
    SpaceToBatchLayer,
    UpsamplingLayer,
    Upsampling1DLayer,
)
from deeplearning4j_tpu.nn.layers.pooling import (  # noqa: F401
    SubsamplingLayer,
    Subsampling1DLayer,
    GlobalPoolingLayer,
)
from deeplearning4j_tpu.nn.layers.norm import (  # noqa: F401
    BatchNormalizationLayer,
    LayerNormalizationLayer,
    LocalResponseNormalizationLayer,
)
from deeplearning4j_tpu.nn.layers.recurrent import (  # noqa: F401
    ConvLSTM2DLayer,
    GRULayer,
    LSTMLayer,
    GravesLSTMLayer,
    GravesBidirectionalLSTMLayer,
    SimpleRnnLayer,
    BidirectionalWrapper,
    LastTimeStepWrapper,
    MaskZeroLayer,
)
from deeplearning4j_tpu.nn.layers.autoencoder import AutoEncoderLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.vae import VariationalAutoencoderLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.vae_distributions import (  # noqa: F401
    BernoulliReconstructionDistribution,
    CompositeReconstructionDistribution,
    ExponentialReconstructionDistribution,
    GaussianReconstructionDistribution,
    LossFunctionWrapper,
    ReconstructionDistribution,
)
from deeplearning4j_tpu.nn.layers.objdetect import (  # noqa: F401
    DetectedObject,
    Yolo2OutputLayer,
    get_predicted_objects,
    nms,
)
from deeplearning4j_tpu.nn.layers.moe import MixtureOfExpertsLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.wrappers import FrozenLayer, TimeDistributedWrapper  # noqa: F401
from deeplearning4j_tpu.nn.layers.samediff import SameDiffLayer, SameDiffLambdaLayer  # noqa: F401
from deeplearning4j_tpu.nn.layers.attention import (  # noqa: F401
    CausalSelfAttentionLayer,
    CrossAttentionLayer,
    SelfAttentionLayer,
    LearnedSelfAttentionLayer,
)
