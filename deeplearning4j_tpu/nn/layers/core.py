"""Core feed-forward layers: Dense, Activation, Dropout, Embedding, …

Reference configs: ``nn/conf/layers/DenseLayer.java``, ``ActivationLayer``,
``DropoutLayer``, ``EmbeddingLayer``/``EmbeddingSequenceLayer``,
``ElementWiseMultiplicationLayer``, ``PReLULayer``. Param names match DL4J's
(``DefaultParamInitializer``: W, b) for checkpoint migration.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer


@register_layer
@dataclasses.dataclass
class DenseLayer(Layer):
    """Fully connected layer: y = act(x @ W + b); W is [n_in, n_out]."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def input_preprocessor(self, input_type: InputType):
        if input_type.kind in ("cnn", "cnn_flat", "cnn3d"):
            flat = input_type.flat_size()
            return (lambda x: x.reshape(x.shape[0], -1), InputType.feed_forward(flat))
        if input_type.kind == "cnn_seq":
            # per-step flatten; dense then applies position-wise
            return input_type.cnn_seq_to_rnn()
        if input_type.kind == "rnn":
            # RnnToFeedForward: fold time into batch [N,T,C] -> [N*T,C]
            return None  # dense applies position-wise below instead
        return None

    def param_shapes(self):
        shapes = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        p = {"W": self._init_w(rng, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = self._init_b((self.n_out,), dtype)
        return p

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._dropout(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class ActivationLayer(Layer):
    """Applies an activation only (``nn/conf/layers/ActivationLayer.java``)."""

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.act_fn()(x), state or {}


@register_layer
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout layer; ``dropout`` is the KEEP probability, DL4J-style.
    If unset here and on the network, defaults to 0.5 at apply time (so the
    network-level dropout default can still flow in via apply_global_defaults).
    """

    def apply_global_defaults(self, g):
        super().apply_global_defaults(g)
        if self.dropout is None:
            self.dropout = 0.5

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self._dropout(x, train, rng), state or {}


@register_layer
@dataclasses.dataclass
class MaskLayer(Layer):
    """Applies the current mask to the activations, otherwise a pass-through
    (``nn/conf/layers/util/MaskLayer.java:24``). Supports 2d feed-forward
    ``[N,F]`` and 4d CNN ``[N,H,W,C]`` activations with a per-example mask
    (``[N]`` / ``[N,1]``), and 3d time series ``[N,T,F]`` with a ``[N,T]``
    step mask. Backward-pass gradients are masked identically for free:
    ``d(m*x)/dx = m`` under autodiff."""

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        if mask is None:
            return x, state or {}
        m = jnp.asarray(mask, x.dtype)
        if m.shape == x.shape:  # full elementwise mask: multiply directly
            return x * m, state or {}
        if (x.ndim == 3 and m.ndim == 2 and m.shape == x.shape[:2]):
            m = m[:, :, None]  # [N,T] step mask → [N,T,1]
        else:  # per-example mask broadcast over all trailing dims
            if m.shape[0] != x.shape[0] or m.size != x.shape[0]:
                raise ValueError(
                    f"MaskLayer: mask shape {m.shape} does not broadcast over "
                    f"input shape {x.shape} (want [N]/[N,1] per-example, or "
                    "[N,T] for 3d time series)")
            m = m.reshape((m.shape[0],) + (1,) * (x.ndim - 1))
        return x * m, state or {}


@register_layer
@dataclasses.dataclass
class EmbeddingLayer(Layer):
    """Index → embedding row (``nn/conf/layers/EmbeddingLayer.java``).

    Input: [N] or [N,1] integer indices; output [N, n_out]. Backprop is a
    scatter-add on the embedding table, which XLA handles natively — no
    hogwild needed.
    """

    n_in: int = 0      # vocab size
    n_out: int = 0
    has_bias: bool = False

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def param_shapes(self):
        shapes = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        p = {"W": self._init_w(rng, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = self._init_b((self.n_out,), dtype)
        return p

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class EmbeddingSequenceLayer(Layer):
    """Sequence of indices → sequence of embeddings
    (``nn/conf/layers/EmbeddingSequenceLayer.java``). Input [N,T] ints →
    output [N,T,n_out] (rnn layout)."""

    n_in: int = 0
    n_out: int = 0
    input_length: Optional[int] = None
    has_bias: bool = False

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, self.input_length or input_type.timesteps)

    def param_shapes(self):
        shapes = {"W": (self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        p = {"W": self._init_w(rng, (self.n_in, self.n_out), self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = self._init_b((self.n_out,), dtype)
        return p

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = jnp.take(params["W"], idx, axis=0)  # [N,T,n_out]
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class ElementWiseMultiplicationLayer(Layer):
    """out = act(x * w + b) elementwise — requires n_in == n_out
    (``nn/conf/layers/misc/ElementWiseMultiplicationLayer.java``)."""

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()
        if not self.n_out:
            self.n_out = self.n_in

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out or self.n_in)

    def param_shapes(self):
        return {"W": (self.n_in,), "b": (self.n_in,)}

    def init_params(self, rng, dtype=jnp.float32):
        return {"W": jnp.ones((self.n_in,), dtype), "b": self._init_b((self.n_in,), dtype)}

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.act_fn()(x * params["W"] + params["b"]), state or {}


@register_layer
@dataclasses.dataclass
class PReLULayer(Layer):
    """Parametric ReLU with learned per-feature alpha
    (``nn/conf/layers/PReLULayer.java``)."""

    input_shape: Optional[Tuple[int, ...]] = None  # feature shape sans batch
    shared_axes: Optional[Tuple[int, ...]] = None

    def set_n_in(self, input_type: InputType) -> None:
        if self.input_shape is None:
            self.input_shape = tuple(input_type.batch_shape(1)[1:])

    def param_shapes(self):
        shape = list(self.input_shape or ())
        if self.shared_axes:
            for ax in self.shared_axes:
                shape[ax - 1] = 1
        return {"W": tuple(shape)}

    def init_params(self, rng, dtype=jnp.float32):
        return {"W": jnp.zeros(self.param_shapes()["W"], dtype)}

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        alpha = params["W"]
        return jnp.where(x >= 0, x, alpha * x), state or {}


@register_layer
@dataclasses.dataclass
class PositionalEmbeddingLayer(BaseRecurrentLayer):
    """Adds a learned position embedding to a sequence: [N,T,C] →
    x + P[:T] with P [max_len, C] (the BERT position-embedding pattern; no
    reference counterpart — the snapshot predates attention, SURVEY.md §5).

    Carries an absolute-position offset under the ``BaseRecurrentLayer``
    protocol so stateful decoding (``rnn_time_step``) and TBPTT chunks add
    the right positions: chunk k starting at absolute position p gets
    P[p:p+T], not P[0:T].
    """

    n_in: int = 0           # feature dim (C)
    max_len: int = 512

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def param_shapes(self):
        return {"P": (self.max_len, self.n_in)}

    def init_params(self, rng, dtype=jnp.float32):
        # BERT-style truncated-normal-ish small init
        return {"P": 0.02 * jax.random.normal(rng, (self.max_len, self.n_in),
                                              dtype)}

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        t = x.shape[1]
        return x + params["P"][:t], state or {}

    def carry_capacity(self):
        return self.max_len

    def init_carry(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((), jnp.int32)  # absolute position offset

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        if carry is None:
            y, _ = self.forward(params, x, mask=mask, train=train, rng=rng)
            return y, None
        t = x.shape[1]
        if not isinstance(carry, jax.core.Tracer) and int(carry) + t > self.max_len:
            raise ValueError(
                f"position overflow: step at offset {int(carry)}+{t} exceeds "
                f"max_len={self.max_len}; raise max_len or "
                f"rnn_clear_previous_state() first")
        p = jax.lax.dynamic_slice(params["P"],
                                  (carry, jnp.zeros((), carry.dtype)),
                                  (t, params["P"].shape[1]))
        return x + p, carry + t
