"""Mixture-of-experts layer with expert-parallel mesh execution.

Not in the reference (SURVEY.md §2.b lists expert parallelism as absent) —
a TPU-first addition: a gated expert FFN layer usable like any other layer,
plus :func:`ep_forward`, which shards the expert dimension over a mesh axis
(each device holds its experts' weights, computes their weighted contribution
for all tokens, and one ``psum`` combines — parameter memory scales 1/E_axis
while the math stays identical to the single-device layer).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer

EXPERT_AXIS = "expert"


def _route(wg, x, top_k: int):
    """Router: dense [..., E] gate vector, top-k renormalized. Shared by the
    single-device apply and the expert-parallel worker so the two paths can
    never diverge."""
    logits = x @ wg                                 # [..., E]
    e = logits.shape[-1]
    k = min(top_k, e)
    top_vals, top_idx = jax.lax.top_k(logits, k)    # [..., k]
    gates_k = jax.nn.softmax(top_vals, axis=-1)     # renormalized over top-k
    return jnp.sum(
        jax.nn.one_hot(top_idx, e, dtype=x.dtype) * gates_k[..., None],
        axis=-2)                                    # [..., E]


def _moe_apply(params, x, top_k: int, act):
    """Dense-compute MoE: every expert runs, gates select/weight.

    x: [..., d_in] → [..., d_out]. Dense all-expert compute keeps shapes
    static (jit-friendly) and is exactly what the EP sharding distributes.
    """
    gates = _route(params["Wg"], x, top_k)
    hidden = jnp.einsum("...d,edh->...eh", x, params["W"]) + params["b"]
    hidden = act(hidden)
    return jnp.einsum("...eh,...e->...h", hidden, gates), gates


@register_layer
@dataclasses.dataclass
class MixtureOfExpertsLayer(Layer):
    """Gated expert FFN: router picks top_k of n_experts per token."""

    n_in: int = 0
    n_out: int = 0
    n_experts: int = 4
    top_k: int = 2
    # opt-in: surface routing gates through the layer state (costs one extra
    # train-step recompile when the state structure changes and serializes
    # the last batch's gates with checkpoints — leave off unless inspecting
    # router behaviour)
    collect_gates: bool = False

    def __post_init__(self):
        if self.activation is None:
            self.activation = "relu"

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size
        if not self.n_out:
            self.n_out = self.n_in

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "recurrent":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def param_shapes(self):
        return {"Wg": (self.n_in, self.n_experts),
                "W": (self.n_experts, self.n_in, self.n_out),
                "b": (self.n_experts, self.n_out)}

    def init_params(self, rng, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        w = jnp.stack([
            self._init_w(k, (self.n_in, self.n_out), self.n_in, self.n_out,
                         dtype)
            for k in jax.random.split(k2, self.n_experts)])
        return {"Wg": self._init_w(k1, (self.n_in, self.n_experts),
                                   self.n_in, self.n_experts, dtype),
                "W": w,
                "b": jnp.zeros((self.n_experts, self.n_out), dtype)}

    def forward(self, params, x, *, state=None, train=False, rng=None,
                mask=None):
        x = self._dropout(x, train, rng)
        out, gates = _moe_apply(params, x, self.top_k, self.act_fn())
        if self.collect_gates:
            new_state = dict(state or {})
            new_state["gates"] = gates
            return out, new_state
        return out, state or {}


def load_balancing_loss(gates: jax.Array) -> jax.Array:
    """Switch-style auxiliary loss: E * sum_e mean_gate_e * dispatch_frac_e,
    where dispatch fraction counts each token toward its top expert —
    minimized (at 1) when routing is uniform across experts.

    To train WITH this aux term, call ``_moe_apply`` (or the layer) inside a
    custom loss (e.g. a SameDiff-style layer/graph) where the gates are part
    of the differentiated computation; ``collect_gates=True`` state capture
    is for *monitoring* only (layer states are non-differentiated aux
    outputs of the train step)."""
    e = gates.shape[-1]
    flat = gates.reshape(-1, e)
    importance = jnp.mean(flat, axis=0)
    top = jax.nn.one_hot(jnp.argmax(flat, axis=-1), e, dtype=flat.dtype)
    dispatch = jnp.mean(top, axis=0)
    return e * jnp.sum(importance * dispatch)


def ep_forward(layer: MixtureOfExpertsLayer, params, x, mesh: Mesh,
               axis_name: str = EXPERT_AXIS):
    """Expert-parallel execution: expert tensors sharded over ``axis_name``.

    Router weights stay replicated (they're tiny); each device computes its
    expert shard's gated contribution for every token and a psum combines.
    Numerically identical to the single-device forward.
    """
    from deeplearning4j_tpu.parallel.mesh import shard_map

    act = layer.act_fn()
    top_k = layer.top_k
    n_exp = layer.n_experts
    n_shards = int(mesh.shape[axis_name])
    if n_exp % n_shards:
        raise ValueError(f"n_experts ({n_exp}) must divide over the "
                         f"{axis_name!r} axis ({n_shards})")
    per = n_exp // n_shards

    def worker(wg, w, b, xx):
        # gating needs ALL experts' logits: router replicated
        gates = _route(wg, xx, top_k)                # [..., E]
        # this shard's slice of the gate vector
        s = jax.lax.axis_index(axis_name)
        local_gates = jax.lax.dynamic_slice_in_dim(
            gates, s * per, per, axis=gates.ndim - 1)
        hidden = jnp.einsum("...d,edh->...eh", xx, w) + b
        hidden = act(hidden)
        partial = jnp.einsum("...eh,...e->...h", hidden, local_gates)
        return jax.lax.psum(partial, axis_name)

    mapped = shard_map(
        worker, mesh=mesh,
        in_specs=(P(), P(axis_name), P(axis_name), P()),
        out_specs=P())
    return mapped(params["Wg"], params["W"], params["b"], jnp.asarray(x))
