"""YOLOv2 output layer for object detection.

Reference: ``nn/conf/layers/objdetect/Yolo2OutputLayer.java`` and its impl
``nn/layers/objdetect/Yolo2OutputLayer.java:71`` (loss of Redmon et al. 2016).
Input is NHWC [N, H, W, B*(5+C)] (grid of B anchor boxes, each with
tx,ty,tw,th,conf + C class scores); labels [N, H, W, B*(5)+...] use the same
packed layout the reference uses: a grid-cell object mask plus target boxes.

Label format here (TPU-simplified but information-equivalent): labels is
[N, H, W, 4 + 1 + C] — (cx, cy, w, h) in grid units with cx/cy ABSOLUTE
grid coordinates (cell index + in-cell offset, matching the decoded
predictions ``sigmoid(tx) + grid_x``), objectness (1 if an object's center
falls in the cell), one-hot class.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (lambda-weighted coord/conf/class terms)."""

    boxes: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),)  # anchor (w,h) priors, grid units
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5
    n_classes: int = 0

    def __post_init__(self):
        if self.activation is None:
            self.activation = "identity"
        if isinstance(self.boxes, list):
            self.boxes = tuple(tuple(b) for b in self.boxes)

    def has_loss(self) -> bool:
        return True

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _split_predictions(self, x):
        """x: [N,H,W,B*(5+C)] → sigmoid/exp-decoded boxes, conf, class logits."""
        n, h, w, _ = x.shape
        b = len(self.boxes)
        c = self.n_classes
        x = x.reshape(n, h, w, b, 5 + c)
        txy = jax.nn.sigmoid(x[..., 0:2])            # offset in cell
        twh = x[..., 2:4]                            # log-space size
        conf = jax.nn.sigmoid(x[..., 4])
        cls_logits = x[..., 5:]
        anchors = jnp.asarray(self.boxes)            # [B,2]
        wh = anchors * jnp.exp(twh)                  # grid units
        grid_x = jnp.arange(w)[None, None, :, None]
        grid_y = jnp.arange(h)[None, :, None, None]
        cx = txy[..., 0] + grid_x
        cy = txy[..., 1] + grid_y
        return cx, cy, wh, conf, cls_logits

    @staticmethod
    def _iou(cx1, cy1, wh1, cx2, cy2, wh2):
        x1min, x1max = cx1 - wh1[..., 0] / 2, cx1 + wh1[..., 0] / 2
        y1min, y1max = cy1 - wh1[..., 1] / 2, cy1 + wh1[..., 1] / 2
        x2min, x2max = cx2 - wh2[..., 0] / 2, cx2 + wh2[..., 0] / 2
        y2min, y2max = cy2 - wh2[..., 1] / 2, cy2 + wh2[..., 1] / 2
        iw = jnp.maximum(jnp.minimum(x1max, x2max) - jnp.maximum(x1min, x2min), 0.0)
        ih = jnp.maximum(jnp.minimum(y1max, y2max) - jnp.maximum(y1min, y2min), 0.0)
        inter = iw * ih
        union = wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter
        return inter / jnp.maximum(union, 1e-8)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return x, state or {}

    def compute_loss(self, params, x, labels, mask=None, conf_target=None):
        """YOLO2 loss. ``conf_target`` (default: ``stop_gradient(iou)``, the
        paper's moving target) can be fixed to a constant [N,H,W,B] array —
        gradient checks use this, because finite differences cannot express
        stop_gradient (they see the target move; autodiff doesn't)."""
        cx, cy, wh, conf, cls_logits = self._split_predictions(x)
        # labels: [N,H,W,5+C]
        lab_cxy = labels[..., 0:2]
        lab_wh = labels[..., 2:4]
        obj = labels[..., 4]                         # [N,H,W]
        lab_cls = labels[..., 5:]

        # responsible box = best IoU with the ground-truth box in each cell
        iou = self._iou(cx, cy, wh, lab_cxy[..., None, 0],
                        lab_cxy[..., None, 1], lab_wh[..., None, :])  # [N,H,W,B]
        best = jnp.argmax(iou, axis=-1)              # [N,H,W]
        resp = jax.nn.one_hot(best, len(self.boxes)) * obj[..., None]  # [N,H,W,B]

        # coordinate loss (sqrt on w,h as in the paper/reference)
        err_xy = (cx - lab_cxy[..., None, 0]) ** 2 + (cy - lab_cxy[..., None, 1]) ** 2
        err_wh = ((jnp.sqrt(jnp.maximum(wh[..., 0], 1e-8)) -
                   jnp.sqrt(jnp.maximum(lab_wh[..., None, 0], 1e-8))) ** 2 +
                  (jnp.sqrt(jnp.maximum(wh[..., 1], 1e-8)) -
                   jnp.sqrt(jnp.maximum(lab_wh[..., None, 1], 1e-8))) ** 2)
        coord_loss = self.lambda_coord * jnp.sum(resp * (err_xy + err_wh))

        # confidence loss: responsible boxes target IoU; others target 0
        target = jax.lax.stop_gradient(
            iou if conf_target is None else conf_target)
        conf_obj = jnp.sum(resp * (conf - target) ** 2)
        conf_noobj = self.lambda_no_obj * jnp.sum((1 - resp) * conf ** 2)

        # classification loss (softmax CE in cells with objects)
        logp = jax.nn.log_softmax(cls_logits, axis=-1)
        cls_loss = -jnp.sum(resp[..., None] * lab_cls[..., None, :] * logp)

        n = x.shape[0]
        return (coord_loss + conf_obj + conf_noobj + cls_loss) / n
