"""YOLOv2 output layer for object detection.

Reference: ``nn/conf/layers/objdetect/Yolo2OutputLayer.java`` and its impl
``nn/layers/objdetect/Yolo2OutputLayer.java:71`` (loss of Redmon et al. 2016).
Input is NHWC [N, H, W, B*(5+C)] (grid of B anchor boxes, each with
tx,ty,tw,th,conf + C class scores); labels [N, H, W, B*(5)+...] use the same
packed layout the reference uses: a grid-cell object mask plus target boxes.

Label format here (TPU-simplified but information-equivalent): labels is
[N, H, W, 4 + 1 + C] — (cx, cy, w, h) in grid units with cx/cy ABSOLUTE
grid coordinates (cell index + in-cell offset, matching the decoded
predictions ``sigmoid(tx) + grid_x``), objectness (1 if an object's center
falls in the cell), one-hot class.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@dataclasses.dataclass
class DetectedObject:
    """One detected object (``objdetect/DetectedObject.java:17``).

    Dimensions are GRID CELL units, like the reference: with 416x416 input
    and 32x downsampling there are 13x13 cells, so ``center_x`` 5.5 means
    5.5*32 = 176 pixels from the left."""

    example: int
    center_x: float
    center_y: float
    width: float
    height: float
    class_predictions: np.ndarray
    confidence: float

    @property
    def predicted_class(self) -> int:
        """Index of the max-probability class (``getPredictedClass``)."""
        return int(np.argmax(np.ravel(self.class_predictions)))

    def top_left_xy(self) -> Tuple[float, float]:
        return (self.center_x - self.width / 2.0,
                self.center_y - self.height / 2.0)

    def bottom_right_xy(self) -> Tuple[float, float]:
        return (self.center_x + self.width / 2.0,
                self.center_y + self.height / 2.0)


def iou(o1: DetectedObject, o2: DetectedObject) -> float:
    """Intersection over union of two detections (``YoloUtils.java:86``)."""
    x1min, y1min = o1.top_left_xy()
    x1max, y1max = o1.bottom_right_xy()
    x2min, y2min = o2.top_left_xy()
    x2max, y2max = o2.bottom_right_xy()
    iw = max(min(x1max, x2max) - max(x1min, x2min), 0.0)
    ih = max(min(y1max, y2max) - max(y1min, y2min), 0.0)
    inter = iw * ih
    union = o1.width * o1.height + o2.width * o2.height - inter
    return 0.0 if union <= 0 else inter / union


def nms(objects: List[DetectedObject], iou_threshold: float
        ) -> List[DetectedObject]:
    """Non-max suppression with the reference's exact semantics
    (``YoloUtils.nms:105``): drop any detection for which a SAME-CLASS
    detection with strictly higher confidence overlaps above the IOU
    threshold. Mutates ``objects`` in place (reference parity) and also
    returns it."""
    keep = list(objects)
    for i, o1 in enumerate(keep):
        if o1 is None:
            continue
        for o2 in keep:
            if (o2 is not None and o1 is not o2
                    and o1.predicted_class == o2.predicted_class
                    and o1.confidence < o2.confidence
                    and iou(o1, o2) > iou_threshold):
                keep[i] = None
                break
    objects[:] = [o for o in keep if o is not None]
    return objects


@functools.partial(jax.jit, static_argnums=(1, 2))
def _decode_detections(output, n_boxes: int, n_classes: int, anchors=None):
    """Device-side decode of RAW Yolo2 output [N,H,W,B*(5+C)] → absolute
    grid-unit boxes + confidences + class probabilities, one fused XLA
    call for the whole batch (the compute half of
    ``YoloUtils.activate:25`` + ``getPredictedObjects:145``)."""
    n, h, w, _ = output.shape
    x = output.reshape(n, h, w, n_boxes, 5 + n_classes).astype(jnp.float32)
    txy = jax.nn.sigmoid(x[..., 0:2])
    cx = txy[..., 0] + jnp.arange(w)[None, None, :, None]
    cy = txy[..., 1] + jnp.arange(h)[None, :, None, None]
    wh = anchors * jnp.exp(x[..., 2:4])
    conf = jax.nn.sigmoid(x[..., 4])
    probs = jax.nn.softmax(x[..., 5:], axis=-1)
    return cx, cy, wh, conf, probs


def get_predicted_objects(boxes, network_output, conf_threshold: float,
                          nms_threshold: float = 0.0,
                          n_classes: Optional[int] = None
                          ) -> List[DetectedObject]:
    """``YoloUtils.getPredictedObjects:144``: RAW network output →
    thresholded, (optionally) NMS-filtered detections.

    TPU-first split: sigmoid/exp/softmax decoding runs as ONE jitted call
    on device for the whole minibatch; only the (few) above-threshold
    candidates come to the host for object construction + NMS.

    ``network_output`` is the layer's raw NHWC activations
    [N, H, W, B*(5+C)] (this framework's Yolo2OutputLayer forward is
    identity, so network ``output()`` == raw scores; the reference's
    separate ``activate`` step is fused into the decode here)."""
    if not 0.0 <= conf_threshold <= 1.0:
        raise ValueError(
            f"Invalid confidence threshold: must be in [0,1], got {conf_threshold}")
    if getattr(network_output, "ndim", None) != 4:
        raise ValueError(
            "Invalid network output activations array: should be rank 4. "
            f"Got shape {getattr(network_output, 'shape', None)}")
    anchors = jnp.asarray(boxes, jnp.float32)
    b = anchors.shape[0]
    if n_classes is None:
        n_classes = network_output.shape[-1] // b - 5
    cx, cy, wh, conf, probs = _decode_detections(
        jnp.asarray(network_output), b, int(n_classes), anchors)
    cx, cy, wh, conf, probs = (np.asarray(a)
                               for a in (cx, cy, wh, conf, probs))
    out: List[DetectedObject] = []
    for i, yy, xx, bb in zip(*np.nonzero(conf >= conf_threshold)):
        out.append(DetectedObject(
            int(i), float(cx[i, yy, xx, bb]), float(cy[i, yy, xx, bb]),
            float(wh[i, yy, xx, bb, 0]), float(wh[i, yy, xx, bb, 1]),
            probs[i, yy, xx, bb].copy(), float(conf[i, yy, xx, bb])))
    if nms_threshold > 0:
        nms(out, nms_threshold)
    return out


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (lambda-weighted coord/conf/class terms)."""

    boxes: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),)  # anchor (w,h) priors, grid units
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5
    n_classes: int = 0

    def __post_init__(self):
        if self.activation is None:
            self.activation = "identity"
        if isinstance(self.boxes, list):
            self.boxes = tuple(tuple(b) for b in self.boxes)

    def has_loss(self) -> bool:
        return True

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _split_predictions(self, x):
        """x: [N,H,W,B*(5+C)] → sigmoid/exp-decoded boxes, conf, class logits."""
        n, h, w, _ = x.shape
        b = len(self.boxes)
        c = self.n_classes
        x = x.reshape(n, h, w, b, 5 + c)
        txy = jax.nn.sigmoid(x[..., 0:2])            # offset in cell
        twh = x[..., 2:4]                            # log-space size
        conf = jax.nn.sigmoid(x[..., 4])
        cls_logits = x[..., 5:]
        anchors = jnp.asarray(self.boxes)            # [B,2]
        wh = anchors * jnp.exp(twh)                  # grid units
        grid_x = jnp.arange(w)[None, None, :, None]
        grid_y = jnp.arange(h)[None, :, None, None]
        cx = txy[..., 0] + grid_x
        cy = txy[..., 1] + grid_y
        return cx, cy, wh, conf, cls_logits

    @staticmethod
    def _iou(cx1, cy1, wh1, cx2, cy2, wh2):
        x1min, x1max = cx1 - wh1[..., 0] / 2, cx1 + wh1[..., 0] / 2
        y1min, y1max = cy1 - wh1[..., 1] / 2, cy1 + wh1[..., 1] / 2
        x2min, x2max = cx2 - wh2[..., 0] / 2, cx2 + wh2[..., 0] / 2
        y2min, y2max = cy2 - wh2[..., 1] / 2, cy2 + wh2[..., 1] / 2
        iw = jnp.maximum(jnp.minimum(x1max, x2max) - jnp.maximum(x1min, x2min), 0.0)
        ih = jnp.maximum(jnp.minimum(y1max, y2max) - jnp.maximum(y1min, y2min), 0.0)
        inter = iw * ih
        union = wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter
        return inter / jnp.maximum(union, 1e-8)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return x, state or {}

    # ------------------------------------------------- detection extraction
    def get_predicted_objects(self, network_output, conf_threshold: float,
                              nms_threshold: float = 0.0
                              ) -> "List[DetectedObject]":
        """Detections from raw network output
        (``nn/layers/objdetect/Yolo2OutputLayer.java:575`` — which passes
        ``nmsThreshold=0.0``; expose it as an argument here)."""
        return get_predicted_objects(self.boxes, network_output,
                                     conf_threshold, nms_threshold,
                                     n_classes=self.n_classes)

    def get_confidence_matrix(self, network_output, example: int,
                              bb_number: int):
        """Decoded confidence for all H/W positions of one anchor box
        (``Yolo2OutputLayer.java:588``), shape [H, W]."""
        anchors = jnp.asarray(self.boxes, jnp.float32)
        _, _, _, conf, _ = _decode_detections(
            jnp.asarray(network_output), anchors.shape[0],
            int(self.n_classes), anchors)
        return conf[example, :, :, bb_number]

    def get_probability_matrix(self, network_output, example: int,
                               class_number: int):
        """Decoded softmax probability of one class for each cell and
        anchor, shape [H, W, B] (``Yolo2OutputLayer.java:604`` — the
        reference returns one class plane; here each anchor carries its own
        softmax, consistently with ``YoloUtils.getPredictedObjects``'s
        B*(5+C) layout, so the anchor axis is kept)."""
        anchors = jnp.asarray(self.boxes, jnp.float32)
        _, _, _, _, probs = _decode_detections(
            jnp.asarray(network_output), anchors.shape[0],
            int(self.n_classes), anchors)
        return probs[example, :, :, :, class_number]

    def compute_loss(self, params, x, labels, mask=None, conf_target=None):
        """YOLO2 loss. ``conf_target`` (default: ``stop_gradient(iou)``, the
        paper's moving target) can be fixed to a constant [N,H,W,B] array —
        gradient checks use this, because finite differences cannot express
        stop_gradient (they see the target move; autodiff doesn't)."""
        cx, cy, wh, conf, cls_logits = self._split_predictions(x)
        # labels: [N,H,W,5+C]
        lab_cxy = labels[..., 0:2]
        lab_wh = labels[..., 2:4]
        obj = labels[..., 4]                         # [N,H,W]
        lab_cls = labels[..., 5:]

        # responsible box = best IoU with the ground-truth box in each cell
        iou = self._iou(cx, cy, wh, lab_cxy[..., None, 0],
                        lab_cxy[..., None, 1], lab_wh[..., None, :])  # [N,H,W,B]
        best = jnp.argmax(iou, axis=-1)              # [N,H,W]
        resp = jax.nn.one_hot(best, len(self.boxes)) * obj[..., None]  # [N,H,W,B]

        # coordinate loss (sqrt on w,h as in the paper/reference)
        err_xy = (cx - lab_cxy[..., None, 0]) ** 2 + (cy - lab_cxy[..., None, 1]) ** 2
        err_wh = ((jnp.sqrt(jnp.maximum(wh[..., 0], 1e-8)) -
                   jnp.sqrt(jnp.maximum(lab_wh[..., None, 0], 1e-8))) ** 2 +
                  (jnp.sqrt(jnp.maximum(wh[..., 1], 1e-8)) -
                   jnp.sqrt(jnp.maximum(lab_wh[..., None, 1], 1e-8))) ** 2)
        coord_loss = self.lambda_coord * jnp.sum(resp * (err_xy + err_wh))

        # confidence loss: responsible boxes target IoU; others target 0
        target = jax.lax.stop_gradient(
            iou if conf_target is None else conf_target)
        conf_obj = jnp.sum(resp * (conf - target) ** 2)
        conf_noobj = self.lambda_no_obj * jnp.sum((1 - resp) * conf ** 2)

        # classification loss (softmax CE in cells with objects)
        logp = jax.nn.log_softmax(cls_logits, axis=-1)
        cls_loss = -jnp.sum(resp[..., None] * lab_cls[..., None, :] * logp)

        n = x.shape[0]
        return (coord_loss + conf_obj + conf_noobj + cls_loss) / n
