"""Denoising AutoEncoder layer (DL4J ``nn/conf/layers/AutoEncoder.java``).

Forward pass in a network = encoder. ``pretrain_loss`` gives the denoising
reconstruction objective used by layerwise pretraining (corruption +
reconstruction cross-entropy/MSE), replacing DL4J's pretrain param phase.
Params follow DL4J's PretrainParamInitializer: W, b (encoder), vb (visible
bias; decoder uses W^T).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import losses as loss_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass
class AutoEncoderLayer(Layer):
    n_in: int = 0
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "sigmoid"

    def is_pretrain_layer(self) -> bool:
        return True

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def param_shapes(self):
        return {"W": (self.n_in, self.n_out), "b": (self.n_out,), "vb": (self.n_in,)}

    def init_params(self, rng, dtype=jnp.float32):
        return {
            "W": self._init_w(rng, (self.n_in, self.n_out), self.n_in, self.n_out, dtype),
            "b": self._init_b((self.n_out,), dtype),
            "vb": jnp.zeros((self.n_in,), dtype),
        }

    def encode(self, params, x):
        return self.act_fn()(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self.act_fn()(h @ params["W"].T + params["vb"])

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.encode(params, x), state or {}

    def pretrain_loss(self, params, x, rng):
        if self.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        else:
            corrupted = x
        recon = self.decode(params, self.encode(params, corrupted))
        fn, _ = loss_mod.resolve(self.loss, None)
        return fn(x, recon)
