"""Convolution layer family — NHWC, lowered straight to XLA convolutions.

Reference configs: ``nn/conf/layers/ConvolutionLayer.java`` (+
``Convolution1DLayer``, ``Deconvolution2D``, ``SeparableConvolution2D``,
``DepthwiseConvolution2D``, ``ZeroPaddingLayer``, ``Cropping2D``,
``SpaceToDepthLayer``, ``SpaceToBatchLayer``, ``Upsampling1D/2D``). The
reference reaches im2col/sconv2d/deconv2d ``DynamicCustomOp``s through the
cuDNN helper seam (``ConvolutionLayer.java:76-84``); here the same math is a
single ``lax.conv_general_dilated`` that XLA tiles onto the MXU — channels
last, so no layout transposes.

Weight layout is HWIO ([kh, kw, in, out]); DL4J's OIHW is converted by the
checkpoint/Keras importers. ConvolutionMode parity: "same" → SAME padding,
"truncate"/"strict" → explicit pad with floor output sizing.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def conv_out_size(size: int, k: int, s: int, p: int, dilation: int, mode: str) -> int:
    if mode == "same":
        return -(-size // s)  # ceil
    eff_k = k + (k - 1) * (dilation - 1)
    return (size + 2 * p - eff_k) // s + 1


@register_layer
@dataclasses.dataclass
class ConvolutionLayer(Layer):
    """2-D convolution (DL4J ConvolutionLayer, NHWC here)."""

    n_in: int = 0   # input channels
    n_out: int = 0  # output channels
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"  # "strict" | "truncate" | "same"
    has_bias: bool = True
    # MLPerf-style stem optimization: rewrite a stride-2 few-channel conv
    # (e.g. ResNet's 7x7/s2 RGB stem) as a space-to-depth block-2 transform +
    # stride-1 conv with 4x the input channels. Mathematically identical
    # (weights stay [kh,kw,C,F] — checkpoints/import unaffected); on the MXU
    # the contraction depth goes 3 -> 12, quadrupling systolic-array
    # utilization for the stem. Opt-in; requires stride (2,2), no "same"
    # padding, dilation 1, kernel <= 8, and even input spatial dims.
    space_to_depth_stem: bool = False

    def __post_init__(self):
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)
        self.dilation = _pair(self.dilation)

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        dh, dw = self.dilation
        mode = self.convolution_mode
        h = conv_out_size(input_type.height, kh, sh, ph, dh, mode)
        w = conv_out_size(input_type.width, kw, sw, pw, dw, mode)
        return InputType.convolutional(h, w, self.n_out)

    def param_shapes(self):
        kh, kw = self.kernel_size
        shapes = {"W": (kh, kw, self.n_in, self.n_out)}
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        p = {"W": self._init_w(rng, (kh, kw, self.n_in, self.n_out), fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = self._init_b((self.n_out,), dtype)
        return p

    def _padding_spec(self):
        if self.convolution_mode == "same":
            return "SAME"
        ph, pw = self.padding
        return [(ph, ph), (pw, pw)]

    def _s2d_applicable(self, x) -> bool:
        return (self.space_to_depth_stem
                and self.stride == (2, 2)
                and self.convolution_mode != "same"
                and self.padding == (0, 0)
                and self.dilation == (1, 1)
                and max(self.kernel_size) <= 8
                and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0)

    def _s2d_forward(self, params, x):
        """out[i,j] = Σ_{u,v} k[u,v]·x[2i+u, 2j+v] regrouped over 2x2 blocks:
        u = 2p+r gives a stride-1 conv of the block-2 space-to-depth input
        with the kernel zero-padded to even size and reblocked to
        [⌈kh/2⌉, ⌈kw/2⌉, 4C, F]."""
        n, h, w, c = x.shape
        kh, kw = self.kernel_size
        f = self.n_out
        xb = x.reshape(n, h // 2, 2, w // 2, 2, c)
        xb = xb.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        kh2, kw2 = -(-kh // 2), -(-kw // 2)
        wk = jnp.pad(params["W"], ((0, 2 * kh2 - kh), (0, 2 * kw2 - kw),
                                   (0, 0), (0, 0)))
        wk = wk.reshape(kh2, 2, kw2, 2, c, f)
        wk = wk.transpose(0, 2, 1, 3, 4, 5).reshape(kh2, kw2, 4 * c, f)
        return lax.conv_general_dilated(
            xb, wk, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._dropout(x, train, rng)
        if self._s2d_applicable(x):
            y = self._s2d_forward(params, x)
        else:
            y = lax.conv_general_dilated(
                x, params["W"],
                window_strides=self.stride,
                padding=self._padding_spec(),
                rhs_dilation=self.dilation,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class Convolution1DLayer(ConvolutionLayer):
    """1-D conv over [N, T, C] (DL4J Convolution1DLayer on rnn-format data)."""

    def __post_init__(self):
        # store geometry as (k, 1) pairs internally
        k = self.kernel_size[0] if isinstance(self.kernel_size, (tuple, list)) else self.kernel_size
        s = self.stride[0] if isinstance(self.stride, (tuple, list)) else self.stride
        p = self.padding[0] if isinstance(self.padding, (tuple, list)) else self.padding
        d = self.dilation[0] if isinstance(self.dilation, (tuple, list)) else self.dilation
        self.kernel_size = (int(k), 1)
        self.stride = (int(s), 1)
        self.padding = (int(p), 0)
        self.dilation = (int(d), 1)

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = conv_out_size(t, self.kernel_size[0], self.stride[0],
                              self.padding[0], self.dilation[0], self.convolution_mode)
        return InputType.recurrent(self.n_out, t)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x4 = x[:, :, None, :]  # [N,T,1,C]
        y, st = super().forward(params, x4, state=state, train=train, rng=rng)
        return y[:, :, 0, :], st


@register_layer
@dataclasses.dataclass
class Deconvolution2DLayer(ConvolutionLayer):
    """Transposed convolution (DL4J Deconvolution2D).

    Implemented as a fractionally-strided conv: dilate the input by the
    stride, spatially flip the kernel, pad with (k-1-p). Output size
    ``s*(in-1) + k - 2p`` matches the reference's deconv2d op.
    """

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        if self.convolution_mode == "same":
            h, w = input_type.height * sh, input_type.width * sw
        else:
            h = sh * (input_type.height - 1) + kh - 2 * ph
            w = sw * (input_type.width - 1) + kw - 2 * pw
        return InputType.convolutional(h, w, self.n_out)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._dropout(x, train, rng)
        kh, kw = self.kernel_size
        ph, pw = self.padding
        if self.convolution_mode == "same":
            # pad so output is exactly input*stride
            out_h = x.shape[1] * self.stride[0]
            out_w = x.shape[2] * self.stride[1]
            dil_h = (x.shape[1] - 1) * self.stride[0] + 1
            dil_w = (x.shape[2] - 1) * self.stride[1] + 1
            tot_h = max(out_h + kh - 1 - dil_h, 0)
            tot_w = max(out_w + kw - 1 - dil_w, 0)
            pad = [(tot_h // 2, tot_h - tot_h // 2), (tot_w // 2, tot_w - tot_w // 2)]
        else:
            pad = [(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)]
        y = lax.conv_general_dilated(
            x, jnp.flip(params["W"], (0, 1)),
            window_strides=(1, 1),
            padding=pad,
            lhs_dilation=self.stride,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class DepthwiseConvolution2DLayer(ConvolutionLayer):
    """Depthwise conv (DL4J DepthwiseConvolution2D): depth_multiplier filters
    per input channel, grouped convolution with groups = n_in."""

    depth_multiplier: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        base = super().output_type(input_type)
        return InputType.convolutional(base.height, base.width, self.n_in * self.depth_multiplier)

    def param_shapes(self):
        kh, kw = self.kernel_size
        shapes = {"W": (kh, kw, 1, self.n_in * self.depth_multiplier)}
        if self.has_bias:
            shapes["b"] = (self.n_in * self.depth_multiplier,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        c_out = self.n_in * self.depth_multiplier
        fan_in = kh * kw
        fan_out = self.depth_multiplier * kh * kw
        p = {"W": self._init_w(rng, (kh, kw, 1, c_out), fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = self._init_b((c_out,), dtype)
        return p

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._dropout(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=self.stride,
            padding=self._padding_spec(),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in,
        )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class SeparableConvolution2DLayer(ConvolutionLayer):
    """Depthwise + pointwise (DL4J SeparableConvolution2D / ND4J sconv2d)."""

    depth_multiplier: int = 1

    def param_shapes(self):
        kh, kw = self.kernel_size
        shapes = {
            "W": (kh, kw, 1, self.n_in * self.depth_multiplier),   # depthwise
            "pW": (1, 1, self.n_in * self.depth_multiplier, self.n_out),  # pointwise
        }
        if self.has_bias:
            shapes["b"] = (self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        k1, k2 = jax.random.split(rng)
        cm = self.n_in * self.depth_multiplier
        p = {
            "W": self._init_w(k1, (kh, kw, 1, cm), kh * kw, self.depth_multiplier * kh * kw, dtype),
            "pW": self._init_w(k2, (1, 1, cm, self.n_out), cm, self.n_out, dtype),
        }
        if self.has_bias:
            p["b"] = self._init_b((self.n_out,), dtype)
        return p

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x = self._dropout(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["W"],
            window_strides=self.stride,
            padding=self._padding_spec(),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in,
        )
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    """Zero padding (DL4J ZeroPaddingLayer). padding = (top, bottom, left, right)
    or (h, w)."""

    padding: Tuple[int, ...] = (0, 0)

    def _pads(self):
        p = self.padding
        if len(p) == 2:
            return (p[0], p[0], p[1], p[1])
        return tuple(p)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self._pads()
        return InputType.convolutional(input_type.height + t + b,
                                       input_type.width + l + r,
                                       input_type.channels)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        t, b, l, r = self._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state or {}


@register_layer
@dataclasses.dataclass
class ZeroPadding1DLayer(Layer):
    """1-D zero padding on [N,T,C] (DL4J ZeroPadding1DLayer)."""

    padding: Tuple[int, int] = (0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = t + self.padding[0] + self.padding[1]
        return InputType.recurrent(input_type.size, t)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        l, r = self.padding
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state or {}


@register_layer
@dataclasses.dataclass
class CropLayer(Layer):
    """Cropping2D equivalent: crop = (top, bottom, left, right)."""

    crop: Tuple[int, ...] = (0, 0, 0, 0)

    def _crops(self):
        c = self.crop
        if len(c) == 2:
            return (c[0], c[0], c[1], c[1])
        return tuple(c)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self._crops()
        return InputType.convolutional(input_type.height - t - b,
                                       input_type.width - l - r,
                                       input_type.channels)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        t, b, l, r = self._crops()
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b or None, l:w - r or None, :], state or {}


@register_layer
@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    """NHWC space-to-depth (DL4J SpaceToDepthLayer / ND4J space_to_depth)."""

    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = self.block_size
        return InputType.convolutional(input_type.height // b, input_type.width // b,
                                       input_type.channels * b * b)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b, c * b * b)
        return y, state or {}


@register_layer
@dataclasses.dataclass
class SpaceToBatchLayer(Layer):
    """NHWC space-to-batch (DL4J SpaceToBatchLayer)."""

    blocks: Tuple[int, int] = (2, 2)
    padding: Tuple[int, ...] = (0, 0, 0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        bh, bw = self.blocks
        p = self.padding if len(self.padding) == 4 else (*self.padding, *self.padding)
        h = (input_type.height + p[0] + p[1]) // bh
        w = (input_type.width + p[2] + p[3]) // bw
        return InputType.convolutional(h, w, input_type.channels)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        bh, bw = self.blocks
        p = self.padding if len(self.padding) == 4 else (*self.padding, *self.padding)
        x = jnp.pad(x, ((0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)))
        n, h, w, c = x.shape
        y = x.reshape(n, h // bh, bh, w // bw, bw, c)
        y = y.transpose(2, 4, 0, 1, 3, 5).reshape(n * bh * bw, h // bh, w // bw, c)
        return y, state or {}


@register_layer
@dataclasses.dataclass
class UpsamplingLayer(Layer):
    """2-D nearest-neighbour upsampling (DL4J Upsampling2D)."""

    size: Tuple[int, int] = (2, 2)

    def __post_init__(self):
        self.size = _pair(self.size)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(input_type.height * self.size[0],
                                       input_type.width * self.size[1],
                                       input_type.channels)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return jnp.repeat(jnp.repeat(x, self.size[0], axis=1), self.size[1], axis=2), state or {}


@register_layer
@dataclasses.dataclass
class Upsampling1DLayer(Layer):
    """1-D upsampling over [N,T,C] (DL4J Upsampling1D)."""

    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return InputType.recurrent(input_type.size, None if t is None else t * self.size)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state or {}
