"""Output / loss layers.

Reference configs: ``nn/conf/layers/OutputLayer.java`` (dense + loss),
``RnnOutputLayer``, ``LossLayer`` (loss only, no params), ``RnnLossLayer``,
``CnnLossLayer``, ``CenterLossOutputLayer``. DL4J's ``BaseOutputLayer``
computes score from the pre-activation ("preOut") so softmax+MCXENT is
numerically fused — ``losses.resolve`` reproduces that: when the loss's
canonical activation matches the layer's, ``compute_loss`` feeds logits.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import losses as loss_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.core import DenseLayer


@register_layer
@dataclasses.dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (DL4J OutputLayer). Default MCXENT+softmax."""

    loss: str = "mcxent"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "softmax"

    def has_loss(self) -> bool:
        return True

    def _preact(self, params, x):
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def compute_loss(self, params, x, labels, mask=None):
        """Loss from this layer's INPUT activations (pre-dense)."""
        pre = self._preact(params, x)
        fn, wants_logits = loss_mod.resolve(self.loss, self.activation)
        out = pre if wants_logits else self.act_fn()(pre)
        return fn(labels, out, mask=mask)


@register_layer
@dataclasses.dataclass
class RnnOutputLayer(OutputLayer):
    """Per-timestep dense + loss over [N,T,*] (DL4J RnnOutputLayer).

    The dense matmul broadcasts over time; per-timestep masks are honored in
    the loss mean exactly like ``LossUtil``/masked score in the reference.
    """

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def compute_loss(self, params, x, labels, mask=None):
        pre = self._preact(params, x)  # [N,T,n_out]
        fn, wants_logits = loss_mod.resolve(self.loss, self.activation)
        out = pre if wants_logits else self.act_fn()(pre)
        return fn(labels, out, mask=mask)


@register_layer
@dataclasses.dataclass
class LossLayer(Layer):
    """Loss-only layer, no params (DL4J LossLayer)."""

    loss: str = "mcxent"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "identity"

    def has_loss(self) -> bool:
        return True

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.act_fn()(x), state or {}

    def compute_loss(self, params, x, labels, mask=None):
        fn, wants_logits = loss_mod.resolve(self.loss, self.activation)
        out = x if wants_logits else self.act_fn()(x)
        return fn(labels, out, mask=mask)


@register_layer
@dataclasses.dataclass
class RnnLossLayer(LossLayer):
    """Per-timestep loss over [N,T,*] (DL4J RnnLossLayer)."""


@register_layer
@dataclasses.dataclass
class CnnLossLayer(LossLayer):
    """Per-pixel loss over NHWC maps (DL4J CnnLossLayer); the feature axis is
    channels, masks broadcast over H,W."""

    def compute_loss(self, params, x, labels, mask=None):
        fn, wants_logits = loss_mod.resolve(self.loss, self.activation)
        out = x if wants_logits else self.act_fn()(x)
        n = out.shape[0]
        out2 = out.reshape(n, -1, out.shape[-1])
        lab2 = labels.reshape(n, -1, labels.shape[-1])
        m2 = None if mask is None else mask.reshape(n, -1)
        return fn(lab2, out2, mask=m2)


@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Output layer with an auxiliary center loss
    (``nn/conf/layers/CenterLossOutputLayer.java``): pulls examples toward a
    learned per-class center. Centers update via gradient here (vs the
    reference's manual SGD-on-centers with ``alpha``), same objective.
    """

    alpha: float = 0.05
    lambda_: float = 2e-4

    def param_shapes(self):
        shapes = super().param_shapes()
        shapes["cL"] = (self.n_out, self.n_in)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        p = super().init_params(rng, dtype)
        p["cL"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def compute_loss(self, params, x, labels, mask=None):
        base = super().compute_loss(params, x, labels, mask)
        # center loss: ||x - c_y||^2 / 2 averaged over batch
        centers = labels @ params["cL"]  # one-hot labels pick centers
        center_l = 0.5 * jnp.mean(jnp.sum((x - centers) ** 2, axis=-1))
        return base + self.lambda_ * center_l
