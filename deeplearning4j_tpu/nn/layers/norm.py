"""Normalization layers: BatchNormalization and LocalResponseNormalization.

Reference: ``nn/conf/layers/BatchNormalization.java`` +
``nn/layers/normalization/BatchNormalization.java`` (running mean/var with
``decay``, gamma/beta optionally locked), ``LocalResponseNormalization.java``.
The cuDNN helper seam (``BatchNormalizationHelper.java:29``) is unnecessary —
XLA fuses the normalize+scale+shift chain into neighbouring ops.

Running statistics are framework "state" (not params): ``forward`` in train
mode returns updated running stats, mirroring DL4J's global-mean/var params
updated during fit.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


def _lowp_moments(x, axes, keepdims=False):
    """f32-ACCUMULATED mean/var for a low-precision (bf16/f16) stream
    without materializing a widened copy of it.

    Each reduce has its own convert as a single-consumer producer, so XLA
    fuses it into the reduction (profiled on ResNet50: a shared
    ``x.astype(f32)`` feeding BOTH reductions materialized and cost ~14% of
    the step). The SQUARE always happens in f32: E[x^2]-E[x]^2 subtracts
    two large numbers, so the x^2 terms need f32 resolution — a bf16-
    rounded square carries error ~2^-9*mean^2, which swamps the true
    variance once |mean| >> std (and f16 outright overflows at |x|>~256).
    Cost measured ~4% of the LN op, invisible at model level; the f32
    accumulator then keeps the summation exact enough.
    """
    cnt = 1
    for a in (axes if isinstance(axes, tuple) else (axes,)):
        cnt *= x.shape[a]
    mean = jnp.sum(x, axis=axes, keepdims=keepdims, dtype=jnp.float32) / cnt
    var = jnp.maximum(
        jnp.sum(jnp.square(x.astype(jnp.float32)), axis=axes,
                keepdims=keepdims, dtype=jnp.float32) / cnt
        - jnp.square(mean), 0.0)
    return mean, var


@register_layer
@dataclasses.dataclass
class BatchNormalizationLayer(Layer):
    """Batch norm over the channel/feature axis (DL4J BatchNormalization).

    DL4J semantics kept: ``decay`` is the running-average momentum
    (running = decay*running + (1-decay)*batch), ``eps`` inside the sqrt,
    optional ``lock_gamma_beta`` trains without scale/shift.
    """

    n_in: int = 0  # feature/channel count
    decay: float = 0.9
    eps: float = 1e-5
    is_minibatch: bool = True
    lock_gamma_beta: bool = False
    gamma_init: float = 1.0
    beta_init: float = 0.0

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            if input_type.kind == "cnn":
                self.n_in = input_type.channels
            else:
                self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def param_shapes(self):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": (self.n_in,), "beta": (self.n_in,)}

    def init_params(self, rng, dtype=jnp.float32):
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.full((self.n_in,), self.gamma_init, dtype),
                "beta": jnp.full((self.n_in,), self.beta_init, dtype)}

    def init_state(self):
        return {"mean": jnp.zeros((self.n_in,), jnp.float32),
                "var": jnp.ones((self.n_in,), jnp.float32)}

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        state = state or self.init_state()
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis (last)
        if train:
            if x.dtype in (jnp.bfloat16, jnp.float16):
                # wide-accumulator single-pass moments (+13% ResNet50
                # training; see _lowp_moments)
                mean, var = _lowp_moments(x, axes)
            else:
                # full-precision inputs keep the two-pass formulation:
                # E[x^2]-E[x]^2 at f32 cancels catastrophically for
                # large-mean features, and there is no convert to save
                mean = jnp.mean(x, axis=axes)
                var = jnp.var(x, axis=axes)
            mean32, var32 = (mean.astype(jnp.float32),
                             var.astype(jnp.float32))
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean32,
                "var": self.decay * state["var"] + (1 - self.decay) * var32,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        # normalize in the activation dtype: f32 stats must not promote a
        # bf16 activation stream back to f32 mid-network
        xhat = (x - mean.astype(x.dtype)) / jnp.sqrt(var.astype(x.dtype) + self.eps)
        if not self.lock_gamma_beta:
            xhat = xhat * params["gamma"] + params["beta"]
        elif self.gamma_init != 1.0 or self.beta_init != 0.0:
            xhat = xhat * self.gamma_init + self.beta_init
        return self.act_fn()(xhat), new_state


@register_layer
@dataclasses.dataclass
class LocalResponseNormalizationLayer(Layer):
    """LRN across channels (DL4J LocalResponseNormalization; AlexNet-era).

    y = x / (k + alpha * sum_{j in window} x_j^2)^beta over the channel axis.
    """

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        # x: NHWC; windowed sum of squares over C via padded cumulative trick
        sq = x * x
        half = self.n // 2
        padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
        # windowed sum via convolution-free slicing (n is tiny, unrolled)
        win = sum(padded[..., i:i + x.shape[-1]] for i in range(self.n))
        denom = (self.k + self.alpha * win) ** self.beta
        return x / denom, state or {}


@register_layer
@dataclasses.dataclass
class LayerNormalizationLayer(Layer):
    """Layer normalization over the last (feature) axis.

    Not present in the reference snapshot (its newest layers predate
    transformers); required here for BERT-style models and Keras
    ``LayerNormalization`` import (BASELINE.md "Keras-import BERT-base").
    """

    n_in: int = 0
    eps: float = 1e-3  # keras LayerNormalization default epsilon

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def param_shapes(self):
        return {"gamma": (self.n_in,), "beta": (self.n_in,)}

    def init_params(self, rng, dtype=jnp.float32):
        return {"gamma": jnp.ones((self.n_in,), dtype),
                "beta": jnp.zeros((self.n_in,), dtype)}

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        if x.dtype in (jnp.bfloat16, jnp.float16):
            # low-precision streams: f32-accumulated moments (plain
            # jnp.mean/var would sum 768+ bf16 terms in bf16); measured
            # 1.24x on the BERT-shape encoder step
            mean, var = _lowp_moments(x, -1, keepdims=True)
            xhat = ((x - mean.astype(x.dtype))
                    * (1.0 / jnp.sqrt(var + self.eps)).astype(x.dtype))
        else:
            mean = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            xhat = (x - mean) / jnp.sqrt(var + self.eps)
        return self.act_fn()(xhat * params["gamma"] + params["beta"]), state or {}
