"""VAE reconstruction distributions — the full reference family.

Reference: ``nn/conf/layers/variational/`` — ``ReconstructionDistribution.java``
(SPI: distributionInputSize / exampleNegLogProbability / generateAtMean /
generateRandom / hasLossFunction), ``BernoulliReconstructionDistribution``,
``GaussianReconstructionDistribution``,
``ExponentialReconstructionDistribution``,
``CompositeReconstructionDistribution.java:27`` (column-partitioned mix),
``LossFunctionWrapper.java`` (plain ILossFunction as "reconstruction error").

Each distribution is a pure-jnp object: per-example negative log probability
is differentiable through ``jax.grad`` (replacing the hand-derived
``gradient()`` methods), and the generate paths run on device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as act_mod

Array = jax.Array

RECONSTRUCTION_REGISTRY: Dict[str, type] = {}


def register_reconstruction(cls):
    RECONSTRUCTION_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class ReconstructionDistribution:
    """SPI (``ReconstructionDistribution.java``)."""

    activation: str = "identity"

    def act(self):
        return act_mod.resolve(self.activation)

    def has_loss_function(self) -> bool:
        return False

    def distribution_input_size(self, data_size: int) -> int:
        raise NotImplementedError

    def example_neg_log_prob(self, x: Array, pre_out: Array) -> Array:
        """Per-example −log p(x | params) (shape [N])."""
        raise NotImplementedError

    def generate_at_mean(self, pre_out: Array) -> Array:
        raise NotImplementedError

    def generate_random(self, rng: jax.Array, pre_out: Array) -> Array:
        raise NotImplementedError

    # -- serde -------------------------------------------------------------
    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if f.name == "distributions" and v is not None:
                v = [[int(sz), dist.to_dict()] for sz, dist in v]
            d[f.name] = v
        d["@recon"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "ReconstructionDistribution":
        d = dict(d)
        cls = RECONSTRUCTION_REGISTRY[d.pop("@recon")]
        if isinstance(d.get("distributions"), list):
            d["distributions"] = [
                (int(sz), ReconstructionDistribution.from_dict(dd))
                for sz, dd in d["distributions"]]
        return cls(**d)


@register_reconstruction
@dataclasses.dataclass
class BernoulliReconstructionDistribution(ReconstructionDistribution):
    """Binary/[0,1] data (``BernoulliReconstructionDistribution.java``);
    default sigmoid activation maps preOut → probabilities. With sigmoid the
    stable softplus-on-logits form is used."""

    activation: str = "sigmoid"

    def distribution_input_size(self, data_size: int) -> int:
        return data_size

    def example_neg_log_prob(self, x, pre_out):
        if self.activation == "sigmoid":
            # -log p = softplus(|l|) + max(l,0) - l*x  (numerically stable)
            nlp = (jnp.maximum(pre_out, 0) - pre_out * x
                   + jnp.log1p(jnp.exp(-jnp.abs(pre_out))))
            return jnp.sum(nlp, axis=-1)
        p = jnp.clip(self.act()(pre_out), 1e-10, 1.0 - 1e-10)
        return -jnp.sum(x * jnp.log(p) + (1 - x) * jnp.log1p(-p), axis=-1)

    def generate_at_mean(self, pre_out):
        return self.act()(pre_out)

    def generate_random(self, rng, pre_out):
        p = self.act()(pre_out)
        return jax.random.bernoulli(rng, p, p.shape).astype(p.dtype)


@register_reconstruction
@dataclasses.dataclass
class GaussianReconstructionDistribution(ReconstructionDistribution):
    """Real-valued data (``GaussianReconstructionDistribution.java``): the
    decoder emits [mean | log σ²] (2× data size); the activation applies to
    the whole pre-out, as in the reference."""

    def distribution_input_size(self, data_size: int) -> int:
        return 2 * data_size

    def _split(self, pre_out):
        out = self.act()(pre_out)
        return jnp.split(out, 2, axis=-1)

    def example_neg_log_prob(self, x, pre_out):
        mean, log_var = self._split(pre_out)
        nlp = 0.5 * (jnp.log(2 * jnp.pi) + log_var
                     + (x - mean) ** 2 / jnp.exp(log_var))
        return jnp.sum(nlp, axis=-1)

    def generate_at_mean(self, pre_out):
        mean, _ = self._split(pre_out)
        return mean

    def generate_random(self, rng, pre_out):
        mean, log_var = self._split(pre_out)
        return mean + jnp.exp(0.5 * log_var) * jax.random.normal(
            rng, mean.shape, mean.dtype)


@register_reconstruction
@dataclasses.dataclass
class ExponentialReconstructionDistribution(ReconstructionDistribution):
    """Data in [0, ∞) (``ExponentialReconstructionDistribution.java``):
    the network models γ = log λ, so −log p(x) = λx − γ. Mean = 1/λ;
    sampling by inverse CDF −log(u)/λ."""

    def distribution_input_size(self, data_size: int) -> int:
        return data_size

    def example_neg_log_prob(self, x, pre_out):
        gamma = self.act()(pre_out)
        lam = jnp.exp(gamma)
        return jnp.sum(lam * x - gamma, axis=-1)

    def generate_at_mean(self, pre_out):
        return jnp.exp(-self.act()(pre_out))  # 1/λ = exp(-γ)

    def generate_random(self, rng, pre_out):
        lam = jnp.exp(self.act()(pre_out))
        u = jax.random.uniform(rng, lam.shape, lam.dtype,
                               minval=1e-10, maxval=1.0)
        return -jnp.log(u) / lam


def _loss_score_array(loss: str, labels: Array, output: Array) -> Array:
    """Per-example loss score column (ILossFunction.computeScoreArray role)
    for the losses LossFunctionWrapper commonly wraps. Matches DL4J's
    per-example semantics: per-element scores summed over the output dim
    after dividing by output size where DL4J's loss does (MSE/MAE)."""
    n_out = labels.shape[-1]
    if loss in ("mse", "l2"):
        per = (labels - output) ** 2
        return jnp.sum(per, axis=-1) / (n_out if loss == "mse" else 1)
    if loss in ("mae", "l1"):
        per = jnp.abs(labels - output)
        return jnp.sum(per, axis=-1) / (n_out if loss == "mae" else 1)
    if loss == "xent":
        p = jnp.clip(output, 1e-10, 1.0 - 1e-10)
        per = -(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
        return jnp.sum(per, axis=-1)
    if loss in ("mcxent", "negativeloglikelihood"):
        p = jnp.clip(output, 1e-10, 1.0)
        return -jnp.sum(labels * jnp.log(p), axis=-1)
    raise ValueError(
        f"LossFunctionWrapper: unsupported loss {loss!r} (supported: mse, "
        "l2, mae, l1, xent, mcxent, negativeloglikelihood)")


@register_reconstruction
@dataclasses.dataclass
class LossFunctionWrapper(ReconstructionDistribution):
    """Use a plain loss function in place of a probability distribution
    (``LossFunctionWrapper.java``). Not probabilistic: reconstruction
    *error* is available, reconstruction *probability* is not (the
    reference throws the same way)."""

    loss: str = "mse"

    def has_loss_function(self) -> bool:
        return True

    def distribution_input_size(self, data_size: int) -> int:
        return data_size

    def example_neg_log_prob(self, x, pre_out):
        # the VAE uses this as its reconstruction cost term; for a wrapped
        # loss that cost is the per-example loss score
        return self.score_array(x, self.act()(pre_out))

    def score_array(self, x, output):
        """Per-example score of OUTPUT (activation already applied —
        CompositeReconstructionDistribution.java's ActivationIdentity note)."""
        return _loss_score_array(self.loss, x, output)

    def generate_at_mean(self, pre_out):
        return self.act()(pre_out)

    def generate_random(self, rng, pre_out):
        # non-probabilistic: "random" generation == the deterministic output
        return self.generate_at_mean(pre_out)


@register_reconstruction
@dataclasses.dataclass
class CompositeReconstructionDistribution(ReconstructionDistribution):
    """Different distributions over column ranges of the data
    (``CompositeReconstructionDistribution.java:27``): ``distributions`` is
    a list of ``(data_size, distribution)`` pairs, in column order."""

    distributions: Optional[List[Tuple[int, ReconstructionDistribution]]] = None

    def __post_init__(self):
        if not self.distributions:
            raise ValueError("CompositeReconstructionDistribution requires "
                             "a non-empty list of (size, distribution) pairs")
        self.distributions = [(int(sz), d) for sz, d in self.distributions]

    @property
    def total_size(self) -> int:
        return sum(sz for sz, _ in self.distributions)

    def has_loss_function(self) -> bool:
        return all(d.has_loss_function() for _, d in self.distributions)

    def distribution_input_size(self, data_size: int) -> int:
        if data_size != self.total_size:
            raise ValueError(
                f"Invalid input size: got {data_size} but the composite's "
                f"distribution sizes sum to {self.total_size} "
                f"({[sz for sz, _ in self.distributions]})")
        return sum(d.distribution_input_size(sz)
                   for sz, d in self.distributions)

    def _slices(self):
        x_at, p_at = 0, 0
        for sz, d in self.distributions:
            psz = d.distribution_input_size(sz)
            yield d, slice(x_at, x_at + sz), slice(p_at, p_at + psz)
            x_at += sz
            p_at += psz

    def example_neg_log_prob(self, x, pre_out):
        total = None
        for d, xs, ps in self._slices():
            part = d.example_neg_log_prob(x[..., xs], pre_out[..., ps])
            total = part if total is None else total + part
        return total

    def score_array(self, x, reconstruction):
        """Summed per-example loss scores (computeLossFunctionScoreArray);
        requires every part to wrap a loss function."""
        if not self.has_loss_function():
            raise ValueError("Cannot compute score array unless every "
                             "component has a loss function")
        total = None
        for d, xs, ps in self._slices():
            part = d.score_array(x[..., xs], reconstruction[..., xs])
            total = part if total is None else total + part
        return total

    def generate_at_mean(self, pre_out):
        return jnp.concatenate(
            [d.generate_at_mean(pre_out[..., ps])
             for d, _, ps in self._slices()], axis=-1)

    def generate_random(self, rng, pre_out):
        outs = []
        for d, _, ps in self._slices():
            rng, k = jax.random.split(rng)
            outs.append(d.generate_random(k, pre_out[..., ps]))
        return jnp.concatenate(outs, axis=-1)


def resolve_reconstruction(v) -> ReconstructionDistribution:
    """Normalize the VAE layer's config value: the legacy string shorthands
    map to default-activation instances; instances pass through."""
    if isinstance(v, ReconstructionDistribution):
        return v
    if isinstance(v, dict) and "@recon" in v:
        return ReconstructionDistribution.from_dict(v)
    name = str(v).lower()
    if name == "bernoulli":
        return BernoulliReconstructionDistribution()
    if name == "gaussian":
        return GaussianReconstructionDistribution()
    if name == "exponential":
        return ExponentialReconstructionDistribution()
    raise ValueError(
        f"Unknown reconstruction distribution {v!r}; use 'bernoulli', "
        "'gaussian', 'exponential', or a ReconstructionDistribution instance")
