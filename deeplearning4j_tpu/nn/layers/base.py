"""Base layer config/impl class and registry.

The reference splits declarative configs (``nn/conf/layers/``) from imperative
impls with hand-written ``backpropGradient`` (``nn/layers/``, e.g.
``Layer.java:38,88``). Here a layer is ONE dataclass:

- hyperparameters (fields; ``None`` means "inherit the network default")
- shape inference (``set_n_in`` / ``output_type`` — DL4J's InputType system)
- ``init_params(rng, dtype)`` → dict of named arrays (DL4J param names kept:
  "W", "b", "gamma", …) — enables DL4J-checkpoint migration
- ``forward(params, x, ...)`` → pure function of (params, inputs);
  backprop is ``jax.grad`` through it.

Mutable-state layers (BatchNorm running stats) thread a ``state`` dict through
``forward`` and return the updated dict; stateless layers return it unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as act_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.updaters import Updater, Schedule
from deeplearning4j_tpu.nn.weights import Distribution, init_weight

Array = jax.Array
Params = Dict[str, Array]
State = Dict[str, Array]

LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class Layer:
    """Common layer hyperparameters (DL4J BaseLayer config fields)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    distribution: Optional[Distribution] = None
    bias_init: Optional[float] = None
    updater: Optional[Updater] = None
    bias_updater: Optional[Updater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[Any] = None  # float keep-prob or IDropout instance
    weight_noise: Optional[Any] = None  # IWeightNoise (DropConnect etc.)
    constraints: Optional[list] = None  # list of LayerConstraint
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    dtype: Optional[Any] = None

    # ---- filled in by the network builder --------------------------------
    def apply_global_defaults(self, g: "Layer") -> None:
        """Inherit unset hyperparams from the global NeuralNetConfiguration."""
        for f in ("activation", "weight_init", "distribution", "bias_init",
                  "updater", "bias_updater", "l1", "l2", "l1_bias", "l2_bias",
                  "dropout", "weight_noise", "gradient_normalization", "dtype"):
            if getattr(self, f) is None and getattr(g, f, None) is not None:
                setattr(self, f, getattr(g, f))
        if self.gradient_normalization_threshold == 1.0 and \
                getattr(g, "gradient_normalization_threshold", 1.0) != 1.0:
            self.gradient_normalization_threshold = g.gradient_normalization_threshold
        if self.constraints is None:
            # builder-level constrain_all/constrain_weights/constrain_bias
            # (NeuralNetConfiguration.java:1031-1060): attach scoped copies
            cs = ([c.scoped("all") for c in getattr(g, "all_constraints", None) or ()]
                  + [c.scoped("weights") for c in getattr(g, "weight_constraints", None) or ()]
                  + [c.scoped("bias") for c in getattr(g, "bias_constraints", None) or ()])
            if cs:
                self.constraints = cs

    # ---- shape inference --------------------------------------------------
    def set_n_in(self, input_type: InputType) -> None:
        """Infer input size from the previous layer's output type."""

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def input_preprocessor(self, input_type: InputType):
        """Return a (fn, new_input_type) preprocessor if this layer needs its
        input reshaped (DL4J's automatic CnnToFeedForward etc.), else None."""
        return None

    # ---- params ------------------------------------------------------------
    def init_params(self, rng: jax.Array, dtype=jnp.float32) -> Params:
        return {}

    def init_state(self) -> State:
        return {}

    def num_params(self) -> int:
        import math
        shapes = self.param_shapes()
        return sum(int(math.prod(s)) for s in shapes.values())

    def param_shapes(self) -> Dict[str, Tuple[int, ...]]:
        return {}

    # ---- forward -----------------------------------------------------------
    def forward(self, params: Params, x: Array, *, state: Optional[State] = None,
                train: bool = False, rng: Optional[jax.Array] = None,
                mask: Optional[Array] = None) -> Tuple[Array, State]:
        raise NotImplementedError

    # ---- misc ---------------------------------------------------------------
    def act_fn(self):
        return act_mod.resolve(self.activation)

    def _dropout(self, x: Array, train: bool, rng: Optional[jax.Array]) -> Array:
        """DL4J-style *input* dropout: a float is the keep probability
        (inverted dropout); any IDropout instance (AlphaDropout,
        GaussianDropout, GaussianNoise, SpatialDropout) applies itself."""
        if not train or self.dropout is None or rng is None:
            return x
        from deeplearning4j_tpu.nn.dropout import resolve_dropout
        d = resolve_dropout(self.dropout)
        return x if d is None else d.apply(x, rng, train)

    def _init_w(self, key, shape, fan_in, fan_out, dtype):
        scheme = self.weight_init or "xavier"
        return init_weight(key, shape, scheme, fan_in, fan_out, dtype,
                           distribution=self.distribution)

    def _init_b(self, shape, dtype):
        return jnp.full(shape, self.bias_init or 0.0, dtype)

    def weight_param_names(self) -> Tuple[str, ...]:
        """Params treated as 'weights' for l1/l2 and weight-updater purposes."""
        return tuple(n for n in self.param_shapes() if n not in ("b", "beta", "gamma", "mean", "var"))

    def bias_param_names(self) -> Tuple[str, ...]:
        return tuple(n for n in self.param_shapes() if n == "b")

    def is_pretrain_layer(self) -> bool:
        return False

    def has_loss(self) -> bool:
        """Output-style layers compute the network loss."""
        return False

    # ---- serde --------------------------------------------------------------
    def to_dict(self) -> dict:
        from deeplearning4j_tpu.nn.constraints import LayerConstraint
        from deeplearning4j_tpu.nn.dropout import IDropout
        from deeplearning4j_tpu.nn.layers.vae_distributions import (
            ReconstructionDistribution)
        from deeplearning4j_tpu.nn.weightnoise import IWeightNoise
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            if isinstance(v, Updater):
                v = v.to_dict()
            elif isinstance(v, Schedule):
                v = v.to_dict()
            elif isinstance(v, Distribution):
                v = v.to_dict()
            elif isinstance(v, (IDropout, IWeightNoise,
                                ReconstructionDistribution)):
                v = v.to_dict()
            elif isinstance(v, Layer):
                v = v.to_dict()
            elif isinstance(v, InputType):
                v = {"@input_type": True, **v.to_dict()}
            elif (isinstance(v, list) and v
                  and all(isinstance(c, LayerConstraint) for c in v)):
                v = [c.to_dict() for c in v]
            d[f.name] = v
        d["@layer"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "Layer":
        return layer_from_dict(d)


def activation_from_config(v):
    """Parameterized activations (``("leakyrelu", {"alpha": …})``) are
    tuples in memory but JSON lists on disk — ONE normalization shared by
    layer and global-conf deserialization."""
    if (isinstance(v, list) and len(v) == 2 and isinstance(v[0], str)
            and isinstance(v[1], dict)):
        return (v[0], dict(v[1]))
    return v


def layer_from_dict(d: dict) -> Layer:
    from deeplearning4j_tpu.nn.dropout import IDropout
    from deeplearning4j_tpu.nn.weightnoise import IWeightNoise
    d = dict(d)
    cls = LAYER_REGISTRY[d.pop("@layer")]
    kw = {}
    for k, v in d.items():
        if isinstance(v, dict) and "@updater" in v:
            v = Updater.from_dict(v)
        elif isinstance(v, dict) and "@schedule" in v:
            v = Schedule.from_dict(v)
        elif isinstance(v, dict) and "@dropout" in v:
            v = IDropout.from_dict(v)
        elif isinstance(v, dict) and "@weight_noise" in v:
            v = IWeightNoise.from_dict(v)
        elif isinstance(v, dict) and "@recon" in v:
            from deeplearning4j_tpu.nn.layers.vae_distributions import (
                ReconstructionDistribution)
            v = ReconstructionDistribution.from_dict(v)
        elif isinstance(v, dict) and "@layer" in v:
            v = layer_from_dict(v)
        elif isinstance(v, dict) and "@input_type" in v:
            v = dict(v)
            v.pop("@input_type")
            v = InputType.from_dict(v)
        elif k == "distribution" and isinstance(v, dict):
            v = Distribution.from_dict(v)
        elif k == "activation":
            v = activation_from_config(v)
        elif (isinstance(v, list) and v
              and all(isinstance(c, dict) and "@constraint" in c for c in v)):
            from deeplearning4j_tpu.nn.constraints import constraints_from_config
            v = constraints_from_config(v)
        kw[k] = v
    # tuples serialize as lists; normalize common geometry fields
    for k in ("kernel_size", "stride", "padding", "dilation", "block_size",
              "blocks", "pad_top_bottom", "crop"):
        if k in kw and isinstance(kw[k], list):
            kw[k] = tuple(kw[k])
    return cls(**kw)
