"""Pooling layers: Subsampling (2D/1D) and GlobalPooling.

Reference configs: ``nn/conf/layers/SubsamplingLayer.java`` (MAX/AVG/SUM/PNORM),
``Subsampling1DLayer``, ``GlobalPoolingLayer`` (pools over spatial or time
dims, mask-aware for variable-length sequences — cf. ``MaskedReductionUtil``).
Implemented with ``lax.reduce_window`` which XLA maps to the TPU vector unit.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.conv import _pair, conv_out_size


@register_layer
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """2-D pooling over NHWC (DL4J SubsamplingLayer)."""

    pooling_type: str = "max"  # "max" | "avg" | "sum" | "pnorm"
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def __post_init__(self):
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)

    def output_type(self, input_type: InputType) -> InputType:
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        h = conv_out_size(input_type.height, kh, sh, ph, 1, self.convolution_mode)
        w = conv_out_size(input_type.width, kw, sw, pw, 1, self.convolution_mode)
        return InputType.convolutional(h, w, input_type.channels)

    def _window(self, x):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = self.padding
            pad = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        return dims, strides, pad

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        dims, strides, pad = self._window(x)
        pt = self.pooling_type.lower()
        if pt == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif pt == "sum":
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
        elif pt == "avg":
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            y = y / (dims[1] * dims[2])
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad)
            y = y ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {self.pooling_type!r}")
        return y, state or {}


@register_layer
@dataclasses.dataclass
class Subsampling1DLayer(SubsamplingLayer):
    """1-D pooling over [N,T,C] (DL4J Subsampling1DLayer)."""

    def __post_init__(self):
        k = self.kernel_size[0] if isinstance(self.kernel_size, (tuple, list)) else self.kernel_size
        s = self.stride[0] if isinstance(self.stride, (tuple, list)) else self.stride
        p = self.padding[0] if isinstance(self.padding, (tuple, list)) else self.padding
        self.kernel_size = (int(k), 1)
        self.stride = (int(s), 1)
        self.padding = (int(p), 0)

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = conv_out_size(t, self.kernel_size[0], self.stride[0], self.padding[0],
                              1, self.convolution_mode)
        return InputType.recurrent(input_type.size, t)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        x4 = x[:, :, None, :]
        y, st = super().forward(params, x4, state=state, train=train, rng=rng)
        return y[:, :, 0, :], st


@register_layer
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over time (rnn) or space (cnn) — DL4J GlobalPoolingLayer.

    Mask-aware: for rnn input with a [N,T] mask, masked steps are excluded
    exactly as ``MaskedReductionUtil`` does.
    """

    pooling_type: str = "max"
    pooling_dimensions: Optional[Tuple[int, ...]] = None
    collapse_dimensions: bool = True
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        return input_type

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        pt = self.pooling_type.lower()
        if x.ndim == 3:  # [N,T,C] over time
            axes = (1,)
        elif x.ndim == 4:  # NHWC over H,W
            axes = (1, 2)
        else:
            raise ValueError(f"GlobalPooling expects 3-D or 4-D input, got {x.shape}")

        if mask is not None and x.ndim == 3:
            m = mask.astype(x.dtype)[:, :, None]
            if pt == "max":
                y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
            elif pt == "sum":
                y = jnp.sum(x * m, axis=1)
            elif pt == "avg":
                y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
            elif pt == "pnorm":
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
            else:
                raise ValueError(pt)
            return y, state or {}

        if pt == "max":
            y = jnp.max(x, axis=axes)
        elif pt == "sum":
            y = jnp.sum(x, axis=axes)
        elif pt == "avg":
            y = jnp.mean(x, axis=axes)
        elif pt == "pnorm":
            p = float(self.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        else:
            raise ValueError(pt)
        return y, state or {}
