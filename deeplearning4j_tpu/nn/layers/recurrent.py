"""Recurrent layers: LSTM / GravesLSTM (peepholes) / SimpleRnn / wrappers.

Reference: ``nn/conf/layers/LSTM.java``, ``GravesLSTM.java``,
``GravesBidirectionalLSTM.java``, ``SimpleRnn``, shared math in
``nn/layers/recurrent/LSTMHelpers.java:58`` (``activateHelper:68``), wrappers
``Bidirectional``, ``LastTimeStep``, ``MaskZeroLayer``. The reference
hand-writes forward+backward per timestep in Java loops; here the recurrence
is one ``lax.scan`` — XLA compiles the whole unrolled graph, and the big
[x,h] @ [W;RW] matmul per step rides the MXU.

Layout: [batch, time, features]; scan runs time-major internally. Gate order
is DL4J's IFOG (input, forget, output, cell-gate). Param names match
``LSTMParamInitializer``: W [n_in, 4H], RW [n_out, 4H] (+3H peephole columns
appended for Graves), b [4H] with forget-gate bias init.

Masking: a [N,T] mask freezes the carried state and zeroes the output at
masked steps (matches DL4J variable-length semantics). TBPTT/stateful
inference use ``forward_seq(params, x, carry)`` which returns the final carry.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn import activations as act_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


# -- fused-LSTM auto-registration (helpers.set_auto_fused_lstm to opt out) ----
# Win region for auto-using PallasLSTMHelper with NO helper registered:
# long sequences with lane-aligned, modest hidden sizes. Measured on v5e the
# fused kernel TIES stock XLA at H=512/T=128 (pallas_kernels.py header) — XLA
# already keeps that carry on-chip — so the auto gate only takes shapes where
# the sequential scan's per-step launch overhead dominates: T >= 256 steps
# and H in {128, 256} (VMEM-resident h/c, one (H,4H) tile per step).
_AUTO_LSTM_MIN_T = 256
_AUTO_LSTM_MAX_H = 256
_auto_lstm_cache: dict = {}


def _auto_lstm_helper():
    """The auto-fallback candidate, or None off the kernel's target backend
    (on CPU the interpreter would be a slowdown, not a win)."""
    if jax.default_backend() != "tpu":
        return None
    h = _auto_lstm_cache.get("std")
    if h is None:
        from deeplearning4j_tpu.nn.pallas_kernels import PallasLSTMHelper
        h = _auto_lstm_cache["std"] = PallasLSTMHelper()
    return h


def _auto_lstm_win_region(layer, x) -> bool:
    return (x.shape[1] >= _AUTO_LSTM_MIN_T
            and layer.n_out % 128 == 0
            and layer.n_out <= _AUTO_LSTM_MAX_H)


def check_carry_capacity(named_layers, t_total: int, context: str) -> None:
    """Reject sequences longer than any finite carry BEFORE a jitted step
    silently clamps a dynamic_update_slice write. One implementation for all
    host-side loops (TBPTT fit, stateful rnn_time_step, generate)."""
    for label, layer in named_layers:
        if isinstance(layer, BaseRecurrentLayer):
            cap = layer.carry_capacity()
            if cap is not None and t_total > cap:
                raise ValueError(
                    f"{context}: sequence length {t_total} exceeds {label} "
                    f"carry capacity {cap}; raise max_cache/max_len, "
                    f"shorten the sequence, or rnn_clear_previous_state()")


class BaseRecurrentLayer(Layer):
    """Mixin API for layers that carry recurrent state."""

    def init_carry(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def carry_capacity(self):
        """Max total timesteps the carry can absorb, or None if unbounded
        (LSTM-style state). Finite-capacity carries (KV caches, positional
        offsets) report it so host-side loops (TBPTT, generate) can reject
        overlong sequences BEFORE a jitted step silently clamps a
        dynamic_update_slice write."""
        return None

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        """[N,T,C] → ([N,T,H], final_carry)."""
        raise NotImplementedError

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        y, _ = self.forward_seq(params, x, carry=None, mask=mask, train=train, rng=rng)
        return y, state or {}

    def input_preprocessor(self, input_type: InputType):
        if input_type.kind == "cnn_seq":
            # image sequences flatten per step for flat-input recurrent layers
            # (ConvLSTM2D overrides this — it consumes [N,T,H,W,C] directly)
            return input_type.cnn_seq_to_rnn()
        return None

    def _scan_seq(self, params, xws, carry, ms):
        """Shared masked scan over time-major precomputed inputs ``xws``
        [T,N,*]; cells implement ``_cell_pre(params, xw_t, carry) ->
        (h, new_carry)``. Masked steps freeze every carry component and zero
        the output (DL4J variable-length semantics) — ONE implementation for
        LSTM/GRU/SimpleRnn so the masking convention cannot drift."""

        def step(c, inp):
            if ms is None:
                h, new_c = self._cell_pre(params, inp, c)
                return new_c, h
            xw_t, m_t = inp
            h, new_c = self._cell_pre(params, xw_t, c)
            m = m_t.reshape(m_t.shape + (1,) * (h.ndim - 1))
            new_c = tuple(m * n + (1 - m) * o for n, o in zip(new_c, c))
            return new_c, h * m

        inputs = xws if ms is None else (xws, ms)
        return lax.scan(step, carry, inputs)


@register_layer
@dataclasses.dataclass
class LSTMLayer(BaseRecurrentLayer, Layer):
    """Standard LSTM (DL4J ``LSTM`` — no peepholes)."""

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"

    peephole = False

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def param_shapes(self):
        h = self.n_out
        # Graves peepholes live in 3 extra RW *columns* (each [H]), matching
        # DL4J's LSTMParamInitializer layout [nOut, 4*nOut+3]
        rw_cols = 4 * h + (3 if self.peephole else 0)
        return {"W": (self.n_in, 4 * h), "RW": (h, rw_cols), "b": (4 * h,)}

    def init_params(self, rng, dtype=jnp.float32):
        h = self.n_out
        k1, k2, k3 = jax.random.split(rng, 3)
        w = self._init_w(k1, (self.n_in, 4 * h), self.n_in, 4 * h, dtype)
        rw_cols = 4 * h + (3 if self.peephole else 0)
        rw = self._init_w(k2, (h, rw_cols), h, rw_cols, dtype)
        b = jnp.zeros((4 * h,), dtype)
        # forget gate block is [h:2h] in IFOG order
        b = b.at[h:2 * h].set(self.forget_gate_bias_init)
        return {"W": w, "RW": rw, "b": b}

    def init_carry(self, batch: int, dtype=jnp.float32):
        h = self.n_out
        return (jnp.zeros((batch, h), dtype), jnp.zeros((batch, h), dtype))

    def _cell(self, params, x_t, carry):
        return self._cell_pre(params, x_t @ params["W"] + params["b"], carry)

    def _cell_pre(self, params, xw_t, carry):
        """Cell step given the precomputed input projection ``x_t @ W + b``.

        The input projection for ALL timesteps is hoisted out of the scan as
        one [N*T, C] x [C, 4H] MXU matmul (XLA cannot batch matmuls across
        scan iterations); only the recurrent h @ RW matmul stays sequential —
        the same split cuDNN's fused RNN uses."""
        h_prev, c_prev = carry
        H = self.n_out
        gate_act = act_mod.resolve(self.gate_activation)
        cell_act = self.act_fn()
        rw = params["RW"][:, :4 * H]
        z = xw_t + h_prev @ rw
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        if self.peephole:
            # per-unit (diagonal) peephole vectors: RW columns 4H, 4H+1, 4H+2
            pi = params["RW"][:, 4 * H]
            pf = params["RW"][:, 4 * H + 1]
            po = params["RW"][:, 4 * H + 2]
            zi = zi + c_prev * pi
            zf = zf + c_prev * pf
        i = gate_act(zi)
        f = gate_act(zf)
        g = cell_act(zg)
        c = f * c_prev + i * g
        if self.peephole:
            zo = zo + c * po
        o = gate_act(zo)
        h = o * cell_act(c)
        return h, (h, c)

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        # helper seam (ConvolutionLayer.java:76-84 reflective-load pattern):
        # a registered LSTM helper (e.g. the Pallas fused kernel) takes the
        # sequence pass when it supports this configuration
        from deeplearning4j_tpu.nn import helpers as _helpers
        helper = _helpers.get_helper("lstm")
        if helper is not None and helper.supports(self, mask):
            return helper.forward_seq(self, params, x, carry)
        if (helper is None and _helpers.auto_fused_lstm_enabled()
                and _auto_lstm_win_region(self, x)):
            # no helper registered: auto-use the fused kernel in its win
            # region (same promotion pattern as the causal-flash fallback in
            # layers/attention.py); opt out via helpers.set_auto_fused_lstm
            cand = _auto_lstm_helper()
            if cand is not None and cand.supports(self, mask):
                return cand.forward_seq(self, params, x, carry)
        n, t, _ = x.shape
        if carry is None:
            carry = self.init_carry(n, x.dtype)
        # hoist the input projection out of the recurrence: one big matmul
        xw = x @ params["W"] + params["b"]           # [N,T,4H] on the MXU
        xws = jnp.swapaxes(xw, 0, 1)                 # [T,N,4H]
        ms = None if mask is None else jnp.swapaxes(mask.astype(x.dtype), 0, 1)  # [T,N]
        final_carry, ys = self._scan_seq(params, xws, carry, ms)
        return jnp.swapaxes(ys, 0, 1), final_carry


@register_layer
@dataclasses.dataclass
class GravesLSTMLayer(LSTMLayer):
    """LSTM with peephole connections (DL4J GravesLSTM)."""

    peephole = True


@register_layer
@dataclasses.dataclass
class SimpleRnnLayer(BaseRecurrentLayer, Layer):
    """Vanilla RNN: h_t = act(x W + h_{t-1} RW + b) (DL4J SimpleRnn)."""

    n_in: int = 0
    n_out: int = 0

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def param_shapes(self):
        return {"W": (self.n_in, self.n_out), "RW": (self.n_out, self.n_out),
                "b": (self.n_out,)}

    def init_params(self, rng, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        return {
            "W": self._init_w(k1, (self.n_in, self.n_out), self.n_in, self.n_out, dtype),
            "RW": self._init_w(k2, (self.n_out, self.n_out), self.n_out, self.n_out, dtype),
            "b": self._init_b((self.n_out,), dtype),
        }

    def init_carry(self, batch: int, dtype=jnp.float32):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        n, t, _ = x.shape
        if carry is None:
            carry = self.init_carry(n, x.dtype)
        # input projection hoisted out of the recurrence (one MXU matmul)
        xws = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)  # [T,N,H]
        ms = None if mask is None else jnp.swapaxes(mask.astype(x.dtype), 0, 1)
        final_carry, ys = self._scan_seq(params, xws, carry, ms)
        return jnp.swapaxes(ys, 0, 1), final_carry

    def _cell_pre(self, params, xw_t, carry):
        (h_prev,) = carry
        h = self.act_fn()(xw_t + h_prev @ params["RW"])
        return h, (h,)


@register_layer
@dataclasses.dataclass
class BidirectionalWrapper(BaseRecurrentLayer, Layer):
    """Bidirectional RNN wrapper (DL4J ``Bidirectional``): runs the wrapped
    recurrent layer forward and on the time-reversed sequence, then combines
    (CONCAT/ADD/MUL/AVERAGE)."""

    layer: Optional[Layer] = None
    mode: str = "concat"  # "concat" | "add" | "mul" | "average"

    def set_n_in(self, input_type: InputType) -> None:
        self.layer.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        if inner.kind == "cnn_seq":  # ConvLSTM2D: combine over channels
            c = inner.channels * 2 if self.mode == "concat" else inner.channels
            return InputType.recurrent_convolutional(inner.height, inner.width,
                                                     c, inner.timesteps)
        size = inner.size * 2 if self.mode == "concat" else inner.size
        return InputType.recurrent(size, inner.timesteps)

    def apply_global_defaults(self, g):
        super().apply_global_defaults(g)
        if self.layer is not None:
            self.layer.apply_global_defaults(g)

    def input_preprocessor(self, input_type: InputType):
        return self.layer.input_preprocessor(input_type)

    def param_shapes(self):
        inner = self.layer.param_shapes()
        return {f"f_{k}": v for k, v in inner.items()} | {f"b_{k}": v for k, v in inner.items()}

    def init_params(self, rng, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        fwd = self.layer.init_params(k1, dtype)
        bwd = self.layer.init_params(k2, dtype)
        return {f"f_{k}": v for k, v in fwd.items()} | {f"b_{k}": v for k, v in bwd.items()}

    def init_carry(self, batch: int, dtype=jnp.float32):
        return (self.layer.init_carry(batch, dtype), self.layer.init_carry(batch, dtype))

    @staticmethod
    def _reverse_masked(x, mask):
        if mask is None:
            return jnp.flip(x, axis=1)
        # reverse only the valid prefix per example (DL4J ReverseOp w/ mask):
        lengths = jnp.sum(mask.astype(jnp.int32), axis=1)  # [N]
        t = x.shape[1]
        idx = jnp.arange(t)[None, :]
        rev_idx = jnp.where(idx < lengths[:, None], lengths[:, None] - 1 - idx, idx)
        rev_idx = rev_idx.reshape(rev_idx.shape + (1,) * (x.ndim - 2))
        return jnp.take_along_axis(x, rev_idx, axis=1)

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        fwd_p = {k[2:]: v for k, v in params.items() if k.startswith("f_")}
        bwd_p = {k[2:]: v for k, v in params.items() if k.startswith("b_")}
        c_f, c_b = carry if carry is not None else (None, None)
        y_f, cf = self.layer.forward_seq(fwd_p, x, carry=c_f, mask=mask, train=train, rng=rng)
        x_rev = self._reverse_masked(x, mask)
        y_b, cb = self.layer.forward_seq(bwd_p, x_rev, carry=c_b, mask=mask, train=train, rng=rng)
        y_b = self._reverse_masked(y_b, mask)
        m = self.mode.lower()
        if m == "concat":
            y = jnp.concatenate([y_f, y_b], axis=-1)
        elif m == "add":
            y = y_f + y_b
        elif m == "mul":
            y = y_f * y_b
        elif m == "average":
            y = 0.5 * (y_f + y_b)
        else:
            raise ValueError(self.mode)
        return y, (cf, cb)


@register_layer
@dataclasses.dataclass
class GravesBidirectionalLSTMLayer(BidirectionalWrapper):
    """DL4J GravesBidirectionalLSTM = Bidirectional(GravesLSTM, CONCAT) with
    ADD combining in the original; reference default combines via CONCAT in
    new API. We expose n_in/n_out directly for config parity."""

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0

    def __post_init__(self):
        if self.layer is None:
            self.layer = GravesLSTMLayer(n_in=self.n_in, n_out=self.n_out,
                                         forget_gate_bias_init=self.forget_gate_bias_init,
                                         activation=self.activation)
        if self.mode == "concat":
            self.mode = "add"  # DL4J GravesBidirectionalLSTM sums directions

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size
        self.layer.n_in = self.n_in
        self.layer.n_out = self.n_out


@register_layer
@dataclasses.dataclass
class LastTimeStepWrapper(Layer):
    """Wraps a recurrent layer, emitting only the last (unmasked) step
    (DL4J ``LastTimeStep``). Not itself a recurrent layer: output is 2-D, so
    it cannot sit inside a TBPTT chunk chain."""

    layer: Optional[Layer] = None

    def set_n_in(self, input_type: InputType) -> None:
        self.layer.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        if inner.kind == "cnn_seq":  # e.g. wrapped ConvLSTM2D → one image
            return InputType.convolutional(inner.height, inner.width, inner.channels)
        return InputType.feed_forward(inner.size)

    def apply_global_defaults(self, g):
        super().apply_global_defaults(g)
        if self.layer is not None:
            self.layer.apply_global_defaults(g)

    def input_preprocessor(self, input_type: InputType):
        return self.layer.input_preprocessor(input_type)

    def param_shapes(self):
        return self.layer.param_shapes()

    def init_params(self, rng, dtype=jnp.float32):
        return self.layer.init_params(rng, dtype)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        y, _ = self.layer.forward_seq(params, x, mask=mask, train=train, rng=rng)
        if mask is None:
            out = y[:, -1]
        else:
            lengths = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1), 1)
            idx = (lengths - 1).reshape((-1,) + (1,) * (y.ndim - 1))
            out = jnp.take_along_axis(y, idx, axis=1)[:, 0]
        return out, state or {}


@register_layer
@dataclasses.dataclass
class MaskZeroLayer(BaseRecurrentLayer, Layer):
    """Sets time steps equal to ``mask_value`` in the input to zero activations
    by constructing a mask (DL4J MaskZeroLayer wrapper)."""

    layer: Optional[Layer] = None
    mask_value: float = 0.0

    def set_n_in(self, input_type: InputType) -> None:
        self.layer.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return self.layer.output_type(input_type)

    def apply_global_defaults(self, g):
        super().apply_global_defaults(g)
        if self.layer is not None:
            self.layer.apply_global_defaults(g)

    def input_preprocessor(self, input_type: InputType):
        return self.layer.input_preprocessor(input_type)

    def param_shapes(self):
        return self.layer.param_shapes()

    def init_params(self, rng, dtype=jnp.float32):
        return self.layer.init_params(rng, dtype)

    def init_carry(self, batch: int, dtype=jnp.float32):
        return self.layer.init_carry(batch, dtype)

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        derived = jnp.any(x != self.mask_value, axis=-1).astype(x.dtype)  # [N,T]
        if mask is not None:
            derived = derived * mask.astype(x.dtype)
        return self.layer.forward_seq(params, x, carry=carry, mask=derived,
                                      train=train, rng=rng)


@register_layer
@dataclasses.dataclass
class GRULayer(BaseRecurrentLayer, Layer):
    """GRU with Keras semantics (needed for Keras-import completeness —
    SURVEY.md §7 hard parts; the reference itself predates GRU).

    Gate order z|r|h in the fused matrices (the Keras kernel layout).
    ``reset_after=True`` (Keras 2+ default) applies the reset gate AFTER the
    recurrent matmul and keeps separate input/recurrent biases (b [2, 3H]);
    ``reset_after=False`` is the classic formulation with one bias [3H].
    """

    n_in: int = 0
    n_out: int = 0
    reset_after: bool = True
    gate_activation: str = "sigmoid"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def param_shapes(self):
        h = self.n_out
        b = (2, 3 * h) if self.reset_after else (3 * h,)
        return {"W": (self.n_in, 3 * h), "RW": (h, 3 * h), "b": b}

    def init_params(self, rng, dtype=jnp.float32):
        h = self.n_out
        k1, k2 = jax.random.split(rng)
        b_shape = (2, 3 * h) if self.reset_after else (3 * h,)
        return {"W": self._init_w(k1, (self.n_in, 3 * h), self.n_in, 3 * h, dtype),
                "RW": self._init_w(k2, (h, 3 * h), h, 3 * h, dtype),
                "b": jnp.zeros(b_shape, dtype)}

    def init_carry(self, batch: int, dtype=jnp.float32):
        return (jnp.zeros((batch, self.n_out), dtype),)

    def _cell_pre(self, params, xw_t, carry):
        (h_prev,) = carry
        H = self.n_out
        gate = act_mod.resolve(self.gate_activation)
        act = self.act_fn()
        if self.reset_after:
            rec = h_prev @ params["RW"] + params["b"][1]
            xz, xr, xh = jnp.split(xw_t, 3, axis=-1)
            rz, rr, rh = jnp.split(rec, 3, axis=-1)
            z = gate(xz + rz)
            r = gate(xr + rr)
            hh = act(xh + r * rh)
        else:
            rw = params["RW"]
            xz, xr, xh = jnp.split(xw_t, 3, axis=-1)
            # one fused matmul for the z|r recurrent contributions
            zr = h_prev @ rw[:, :2 * H]
            z = gate(xz + zr[:, :H])
            r = gate(xr + zr[:, H:])
            hh = act(xh + (r * h_prev) @ rw[:, 2 * H:])
        h = z * h_prev + (1.0 - z) * hh
        return h, (h,)

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        n, t, _ = x.shape
        if carry is None:
            carry = self.init_carry(n, x.dtype)
        b_in = params["b"][0] if self.reset_after else params["b"]
        # input projection hoisted out of the recurrence (one MXU matmul)
        xws = jnp.swapaxes(x @ params["W"] + b_in, 0, 1)  # [T,N,3H]
        ms = None if mask is None else jnp.swapaxes(mask.astype(x.dtype), 0, 1)
        final_carry, ys = self._scan_seq(params, xws, carry, ms)
        return jnp.swapaxes(ys, 0, 1), final_carry


@register_layer
@dataclasses.dataclass
class ConvLSTM2DLayer(BaseRecurrentLayer, Layer):
    """Convolutional LSTM over image sequences [N, T, H, W, C] (Keras
    ``ConvLSTM2D`` semantics; needed for Keras-import completeness — the
    reference itself has no ConvLSTM, its recurrent family stops at LSTM
    variants, ``nn/conf/layers/``).

    Gates are convolutions instead of matmuls: the input convolution for ALL
    timesteps is hoisted out of the scan as one [N*T,H,W,C] conv (the MXU
    sees one big batched conv); only the recurrent conv of h stays
    sequential. Gate order is IFOG along the channel axis, matching our LSTM,
    so the Keras importer reuses the same i|f|c|o → i|f|o|g reorder.

    Weights: W [kh,kw,C,4F] (input conv, stride/padding per config),
    RW [kh,kw,F,4F] (recurrent conv, always stride 1 / SAME), b [4F].
    """

    n_in: int = 0   # input channels
    n_out: int = 0  # filters
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"  # "truncate" (valid) | "same"
    has_bias: bool = True
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def __post_init__(self):
        if self.activation is None:
            self.activation = "tanh"
        pair = lambda v: (int(v[0]), int(v[1])) if isinstance(v, (tuple, list)) else (int(v), int(v))
        self.kernel_size = pair(self.kernel_size)
        self.stride = pair(self.stride)
        self.padding = pair(self.padding)
        self.dilation = pair(self.dilation)
        self._out_hw = None  # set by output_type during config build

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.channels

    def input_preprocessor(self, input_type: InputType):
        return None  # consumes [N,T,H,W,C] directly

    def output_type(self, input_type: InputType) -> InputType:
        from deeplearning4j_tpu.nn.layers.conv import conv_out_size
        kh, kw = self.kernel_size
        sh, sw = self.stride
        ph, pw = self.padding
        dh, dw = self.dilation
        h = conv_out_size(input_type.height, kh, sh, ph, dh, self.convolution_mode)
        w = conv_out_size(input_type.width, kw, sw, pw, dw, self.convolution_mode)
        self._out_hw = (h, w)
        return InputType.recurrent_convolutional(h, w, self.n_out,
                                                 input_type.timesteps)

    def param_shapes(self):
        kh, kw = self.kernel_size
        shapes = {"W": (kh, kw, self.n_in, 4 * self.n_out),
                  "RW": (kh, kw, self.n_out, 4 * self.n_out)}
        if self.has_bias:
            shapes["b"] = (4 * self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        f = self.n_out
        k1, k2 = jax.random.split(rng)
        p = {"W": self._init_w(k1, (kh, kw, self.n_in, 4 * f),
                               self.n_in * kh * kw, 4 * f * kh * kw, dtype),
             "RW": self._init_w(k2, (kh, kw, f, 4 * f),
                                f * kh * kw, 4 * f * kh * kw, dtype)}
        if self.has_bias:
            b = jnp.zeros((4 * f,), dtype)
            p["b"] = b.at[f:2 * f].set(self.forget_gate_bias_init)
        return p

    def init_carry(self, batch: int, dtype=jnp.float32):
        if self._out_hw is None:
            raise ValueError(
                "ConvLSTM2DLayer carry shape is unknown until output_type() "
                "has run (build the layer inside a network config)")
        h, w = self._out_hw
        z = jnp.zeros((batch, h, w, self.n_out), dtype)
        return (z, z)

    def _padding_spec(self):
        if self.convolution_mode == "same":
            return "SAME"
        ph, pw = self.padding
        return [(ph, ph), (pw, pw)]

    def _cell_pre(self, params, xw_t, carry):
        h_prev, c_prev = carry
        gate = act_mod.resolve(self.gate_activation)
        act = self.act_fn()
        z = xw_t + lax.conv_general_dilated(
            h_prev, params["RW"], window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        i = gate(zi)
        f = gate(zf)
        g = act(zg)
        c = f * c_prev + i * g
        o = gate(zo)
        h = o * act(c)
        return h, (h, c)

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        n, t = x.shape[:2]
        xf = x.reshape((n * t,) + x.shape[2:])
        z = lax.conv_general_dilated(
            xf, params["W"], window_strides=self.stride,
            padding=self._padding_spec(), rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        ho, wo = z.shape[1], z.shape[2]
        xws = jnp.swapaxes(z.reshape(n, t, ho, wo, 4 * self.n_out), 0, 1)
        if carry is None:
            zero = jnp.zeros((n, ho, wo, self.n_out), x.dtype)
            carry = (zero, zero)
        ms = None if mask is None else jnp.swapaxes(mask.astype(x.dtype), 0, 1)
        final_carry, ys = self._scan_seq(params, xws, carry, ms)
        return jnp.swapaxes(ys, 0, 1), final_carry
