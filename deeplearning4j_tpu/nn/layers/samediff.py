"""SameDiff-style custom layer SPI.

Reference: ``nn/conf/layers/samediff/AbstractSameDiffLayer.java`` /
``BaseSameDiffLayer.java`` and impl ``nn/layers/samediff/SameDiffLayer.java:19``
(``defineLayer:209``) — users declare params and define the forward graph in
SameDiff ops; DL4J autodiffs it. The JAX analog is direct: subclass, declare
``define_parameters``, write ``define_layer`` in jnp — ``jax.grad`` supplies
the backward pass, jit the whole network as usual.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass
class SameDiffLayer(Layer):
    """Subclass and override ``define_parameters`` + ``define_layer``.

    Example::

        @register_layer
        @dataclasses.dataclass
        class MyLayer(SameDiffLayer):
            n_in: int = 0
            n_out: int = 0
            def define_parameters(self):
                return {"W": (self.n_in, self.n_out), "b": (self.n_out,)}
            def define_layer(self, params, x):
                return jnp.tanh(x @ params["W"] + params["b"])
    """

    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        if self.n_out:
            return InputType.feed_forward(self.n_out)
        return input_type

    # -- SPI ----------------------------------------------------------------
    def define_parameters(self) -> Dict[str, Tuple[int, ...]]:
        return {}

    def define_layer(self, params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
        raise NotImplementedError

    # -- plumbing -----------------------------------------------------------
    def param_shapes(self):
        return self.define_parameters()

    def init_params(self, rng, dtype=jnp.float32):
        shapes = self.define_parameters()
        if not shapes:
            return {}
        keys = jax.random.split(rng, len(shapes))
        out = {}
        for (name, shape), k in zip(shapes.items(), keys):
            if name == "b" or (len(shape) == 1 and name.endswith("b")):
                out[name] = jnp.zeros(shape, dtype)
            else:
                fan_in = shape[0] if len(shape) >= 1 else 1
                fan_out = shape[-1] if len(shape) >= 2 else shape[0]
                out[name] = self._init_w(k, shape, fan_in, fan_out, dtype)
        return out

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        return self.define_layer(params, x), state or {}


@register_layer
@dataclasses.dataclass
class SameDiffLambdaLayer(SameDiffLayer):
    """Parameterless lambda layer (DL4J SameDiffLambdaLayer): wraps a pure
    function of the input. Not JSON-serializable unless the fn is re-attached
    after deserialization."""

    fn: Optional[Callable[[jax.Array], jax.Array]] = None

    def define_parameters(self):
        return {}

    def define_layer(self, params, x):
        return self.fn(x)

    def to_dict(self):
        d = super().to_dict()
        d.pop("fn", None)
        return d
