"""Self-attention layers.

The reference snapshot has no attention layer (SURVEY.md §5 long-context), but
BASELINE.json's BERT-import config requires attention ops; DL4J's later
releases added ``SelfAttentionLayer``/``LearnedSelfAttentionLayer`` on
SameDiff. Built TPU-first: one fused QKV projection (single MXU matmul),
scaled dot-product attention with optional masking, bf16-friendly. The op is
sequence-shardable — see ``parallel/ring.py`` for the ring-attention variant
used under sequence parallelism.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.recurrent import BaseRecurrentLayer


#: causal flash auto-use threshold — below this the einsum path ties or
#: wins (measured v5e; see PallasFlashAttentionHelper docstring)
_AUTO_FLASH_MIN_T = 2048
_auto_flash_cache: dict = {}


def _auto_flash_helper():
    h = _auto_flash_cache.get("causal")
    if h is None:
        from deeplearning4j_tpu.nn.pallas_kernels import (
            PallasFlashAttentionHelper)
        h = _auto_flash_cache["causal"] = PallasFlashAttentionHelper(
            causal=True)
    return h


def dot_product_attention(q, k, v, mask=None, dropout_rate=0.0, rng=None,
                          train=False, causal=False):
    """q,k,v: [N, H, T, Dh]; mask: [N, T] (1=valid) or [N, 1, Tq, Tk];
    ``causal=True`` additionally lower-triangular-masks the scores.

    Consults the "attention" helper seam first: a registered fused kernel
    (e.g. PallasFlashAttentionHelper) takes supported shapes — causality is
    part of the request, so a helper only serves requests whose semantics it
    reproduces; otherwise the einsum path below runs (and XLA fuses it).
    """
    from deeplearning4j_tpu.nn import helpers as _helpers
    helper = _helpers.get_helper("attention")
    dropout_active = bool(train and dropout_rate > 0 and rng is not None)
    if (helper is not None
            and helper.supports(None, q.shape, mask, dropout_active,
                                causal=causal)
            and q.shape == k.shape == v.shape):
        return helper.attend(q, k, v)
    if (helper is None and causal and q.shape[-2] >= _AUTO_FLASH_MIN_T
            and _helpers.auto_flash_attention_enabled()):
        # no helper registered: auto-use the causal flash kernel in its
        # measured win region (1.45x T=2048 / 2.64x T=4096 LM training) so
        # the speedup doesn't depend on knowing the seam exists; opt out
        # via helpers.set_auto_flash_attention(False)
        cand = _auto_flash_helper()
        if (cand.supports(None, q.shape, mask, dropout_active, causal=True)
                and q.shape == k.shape == v.shape):
            return cand.attend(q, k, v)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    m = None
    if mask is not None:
        m = (mask[:, None, None, :] if mask.ndim == 2 else mask) > 0
    if causal:
        tri = jnp.tril(jnp.ones((q.shape[-2], k.shape[-2]), bool))[None, None]
        m = tri if m is None else jnp.logical_and(m, tri)
    if m is not None:
        scores = jnp.where(m, scores, jnp.finfo(scores.dtype).min)
    w = jax.nn.softmax(scores, axis=-1)
    if train and dropout_rate > 0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("nhqk,nhkd->nhqd", w, v)


@register_layer
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Multi-head self-attention over [N,T,C] with optional output projection."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: Optional[int] = None
    project_input: bool = True
    attn_dropout: float = 0.0
    causal: bool = False

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.size
        if not self.n_out:
            self.n_out = self.n_in

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def _dh(self):
        return self.head_size or self.n_out // self.n_heads

    def param_shapes(self):
        # Wqkv columns are HEAD-MAJOR [H, 3, Dh] (each head's q|k|v block
        # contiguous), NOT [3, H, Dh]: a column-sharded Wqkv then propagates
        # through the (n,t,h,3,dh) reshape under GSPMD whenever tp divides
        # n_heads, keeping tensor-parallel attention at one all-reduce per
        # block. The [3,H,Dh] order measured 5 extra qkv all-gathers on a
        # tp=4 mesh (tests/test_parallel.py::test_attention_collectives).
        dh = self._dh()
        inner = self.n_heads * dh
        shapes = {"Wqkv": (self.n_in, 3 * inner), "bqkv": (3 * inner,)}
        if self.project_input:
            shapes["Wo"] = (inner, self.n_out)
            shapes["bo"] = (self.n_out,)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        dh = self._dh()
        inner = self.n_heads * dh
        if not self.project_input and inner != self.n_out:
            raise ValueError(
                f"project_input=False requires n_heads*head_size == n_out "
                f"(got {inner} != {self.n_out})")
        k1, k2 = jax.random.split(rng)
        p = {
            "Wqkv": self._init_w(k1, (self.n_in, 3 * inner), self.n_in, 3 * inner, dtype),
            "bqkv": jnp.zeros((3 * inner,), dtype),
        }
        if self.project_input:
            p["Wo"] = self._init_w(k2, (inner, self.n_out), inner, self.n_out, dtype)
            p["bo"] = jnp.zeros((self.n_out,), dtype)
        return p

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        n, t, _ = x.shape
        h, dh = self.n_heads, self._dh()
        qkv = x @ params["Wqkv"] + params["bqkv"]              # [N,T,H*3*Dh]
        qkv = qkv.reshape(n, t, h, 3, dh).transpose(3, 0, 2, 1, 4)  # [3,N,H,T,Dh]
        q, k, v = qkv[0], qkv[1], qkv[2]
        out = dot_product_attention(q, k, v, mask=mask, causal=self.causal,
                                    dropout_rate=self.attn_dropout,
                                    rng=rng, train=train)
        y = out.transpose(0, 2, 1, 3).reshape(n, t, h * dh)
        if self.project_input:
            y = y @ params["Wo"] + params["bo"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class CausalSelfAttentionLayer(SelfAttentionLayer, BaseRecurrentLayer):
    """Causal (autoregressive) multi-head self-attention.

    No reference counterpart — the snapshot predates attention (SURVEY.md §5);
    this is the decoder-side twin of :class:`SelfAttentionLayer`, required for
    the text-generation transformer in the zoo. Two execution modes:

    - ``forward`` (training / full-sequence): one fused QKV matmul, scores
      masked with the lower-triangular causal mask ∧ the padding mask. XLA
      fuses mask+softmax into the attention einsums.
    - ``forward_seq`` with a carry (stateful decoding via ``rnn_time_step``):
      a fixed-capacity KV cache — (k_cache, v_cache, key_validity, position),
      all static shapes so the step jits once and new tokens are written with
      ``lax.dynamic_update_slice``. Decoding T new tokens costs O(T·max_cache)
      instead of re-running the full quadratic attention per step.

    The carry rides the same ``BaseRecurrentLayer`` protocol the LSTMs use, so
    ``MultiLayerNetwork.rnn_time_step`` / ``ComputationGraph.rnn_time_step``
    (rnnTimeStep:2800 parity) and TBPTT chunking (the chunk attends over all
    cached previous chunks, Transformer-XL style) work unchanged.
    """

    max_cache: int = 512
    causal: bool = True  # full-sequence forward = SelfAttentionLayer's, masked

    # ------------------------------------------------- stateful decode path
    def carry_capacity(self):
        return self.max_cache

    def init_carry(self, batch: int, dtype=jnp.float32):
        h, dh, tc = self.n_heads, self._dh(), self.max_cache
        return (jnp.zeros((batch, h, tc, dh), dtype),   # K cache
                jnp.zeros((batch, h, tc, dh), dtype),   # V cache
                jnp.zeros((batch, tc), dtype),          # key validity
                jnp.zeros((), jnp.int32))               # write position

    def forward_seq(self, params, x, carry=None, mask=None, train=False, rng=None):
        if carry is None:
            y, _ = self.forward(params, x, train=train, rng=rng, mask=mask)
            return y, None
        n, t, _ = x.shape
        h, dh, tc = self.n_heads, self._dh(), self.max_cache
        kc, vc, valid, pos = carry
        if not isinstance(pos, jax.core.Tracer) and int(pos) + t > tc:
            raise ValueError(
                f"KV cache overflow: writing {t} token(s) at position "
                f"{int(pos)} exceeds max_cache={tc}; raise max_cache or "
                f"rnn_clear_previous_state() first")
        qkv = x @ params["Wqkv"] + params["bqkv"]
        qkv = qkv.reshape(n, t, h, 3, dh).transpose(3, 0, 2, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        zero = jnp.zeros((), pos.dtype)  # match pos dtype (x64 mode safe)
        kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                          (zero, zero, pos, zero))
        vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                          (zero, zero, pos, zero))
        block_valid = (jnp.ones((n, t)) if mask is None
                       else (mask[:, :t] > 0)).astype(valid.dtype)
        valid = jax.lax.dynamic_update_slice(valid, block_valid, (zero, pos))
        # query i (absolute position pos+i) may see cache slots <= pos+i that
        # hold valid keys
        causal = jnp.arange(tc)[None, :] <= (pos + jnp.arange(t))[:, None]
        m = jnp.logical_and(causal[None, None], (valid > 0)[:, None, None, :])
        scale = 1.0 / jnp.sqrt(jnp.asarray(dh, q.dtype))
        scores = jnp.einsum("nhqd,nhkd->nhqk", q, kc.astype(q.dtype)) * scale
        scores = jnp.where(m, scores, jnp.finfo(scores.dtype).min)
        w = jax.nn.softmax(scores, axis=-1)
        if train and self.attn_dropout > 0 and rng is not None:
            # TBPTT training through the cache must regularize like the
            # full-sequence path
            keep = jax.random.bernoulli(rng, 1.0 - self.attn_dropout, w.shape)
            w = jnp.where(keep, w / (1.0 - self.attn_dropout), 0.0)
        out = jnp.einsum("nhqk,nhkd->nhqd", w, vc.astype(q.dtype))
        y = out.transpose(0, 2, 1, 3).reshape(n, t, h * dh)
        if self.project_input:
            y = y @ params["Wo"] + params["bo"]
        return self.act_fn()(y), (kc, vc, valid, pos + t)


#: stamped into checkpoint metadata by the serializers; its absence marks a
#: pre-round-5 checkpoint whose fused attention weights use the legacy
#: [3|2, H, Dh] block-major column order and need repacking on load
QKV_LAYOUT = "head_major"

_FUSED_PARTS = {"Wqkv": 3, "bqkv": 3, "Wkv": 2, "bkv": 2}

#: updater-state slots that are elementwise per-parameter accumulators and
#: therefore share the param's fused-column indexing (every slot the
#: nn/updaters.py registry defines: momentum/velocity and the various
#: squared-gradient accumulators). Only these repack with the param — a
#: future same-shaped slot that is NOT column-indexed must be added here
#: explicitly, never permuted by a shape match.
_COLUMN_INDEXED_SLOTS = frozenset(
    {"v", "m", "u", "h", "v_hat", "eg2", "edx2", "g2"})


def repack_legacy_fused_qkv(model) -> int:
    """Migrate a model whose attention params were saved in the pre-round-5
    block-major fused order ([3,H,Dh] / [2,H,Dh] columns) to the current
    head-major order ([H,3,Dh] / [H,2,Dh] — the layout that lets a
    column-sharded Wqkv propagate through the qkv reshape under GSPMD).
    Repacks params AND matching updater-state slots in place; returns the
    number of arrays repacked. Called by the checkpoint restorers when the
    checkpoint metadata carries no ``qkv_layout`` stamp."""
    import numpy as np

    def layer_items():
        if isinstance(model.params, dict):
            for name, vd in model.conf.vertices.items():
                if vd.is_layer and name in model.params:
                    yield name, vd.obj
        else:
            for i, layer in enumerate(model.layers):
                yield i, layer

    def repack(arr, parts, h, dh):
        a = np.asarray(arr)
        if a.ndim == 1:
            return jnp.asarray(
                a.reshape(parts, h, dh).transpose(1, 0, 2).reshape(-1))
        d = a.shape[0]
        return jnp.asarray(
            a.reshape(d, parts, h, dh).transpose(0, 2, 1, 3).reshape(d, -1))

    n_repacked = 0
    for key, layer in layer_items():
        if not isinstance(layer, SelfAttentionLayer):
            continue
        h, dh = layer.n_heads, layer._dh()
        if h <= 1:
            continue  # single head: both layouts are identical
        pd = model.params[key]
        for pn, parts in _FUSED_PARTS.items():
            if pn not in pd:
                continue
            pd[pn] = repack(pd[pn], parts, h, dh)
            n_repacked += 1
            upd = model.updater_states[key].get(pn, {}) \
                if model.updater_states is not None else {}
            for slot, arr in upd.items():
                if (slot in _COLUMN_INDEXED_SLOTS
                        and np.asarray(arr).shape
                        == np.asarray(pd[pn]).shape):
                    upd[slot] = repack(arr, parts, h, dh)
                    n_repacked += 1
    return n_repacked


@register_layer
@dataclasses.dataclass
class LearnedSelfAttentionLayer(SelfAttentionLayer):
    """Attention with n_queries learned query vectors (DL4J
    LearnedSelfAttentionLayer): output is [N, n_queries, n_out]."""

    n_queries: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, self.n_queries)

    def param_shapes(self):
        dh = self._dh()
        inner = self.n_heads * dh
        return {"Wkv": (self.n_in, 2 * inner), "bkv": (2 * inner,),
                "Q": (self.n_queries, self.n_heads, dh),
                "Wo": (inner, self.n_out), "bo": (self.n_out,)}

    def init_params(self, rng, dtype=jnp.float32):
        dh = self._dh()
        inner = self.n_heads * dh
        k1, k2, k3 = jax.random.split(rng, 3)
        return {
            "Wkv": self._init_w(k1, (self.n_in, 2 * inner), self.n_in, 2 * inner, dtype),
            "bkv": jnp.zeros((2 * inner,), dtype),
            "Q": self._init_w(k2, (self.n_queries, self.n_heads, dh), dh, dh, dtype),
            "Wo": self._init_w(k3, (inner, self.n_out), inner, self.n_out, dtype),
            "bo": jnp.zeros((self.n_out,), dtype),
        }

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        n, t, _ = x.shape
        h, dh = self.n_heads, self._dh()
        kv = x @ params["Wkv"] + params["bkv"]  # head-major [H,2,Dh] columns
        kv = kv.reshape(n, t, h, 2, dh).transpose(3, 0, 2, 1, 4)
        k, v = kv[0], kv[1]
        q = jnp.broadcast_to(params["Q"].transpose(1, 0, 2)[None], (n, h, self.n_queries, dh))
        out = dot_product_attention(q, k, v, mask=mask, dropout_rate=self.attn_dropout,
                                    rng=rng, train=train)
        out = out.transpose(0, 2, 1, 3).reshape(n, self.n_queries, h * dh)
        y = out @ params["Wo"] + params["bo"]
        return self.act_fn()(y), state or {}


@register_layer
@dataclasses.dataclass
class CrossAttentionLayer(Layer):
    """Multi-head cross-attention: query from one graph input, key/value from
    another (Keras ``MultiHeadAttention(query, value[, key])`` semantics —
    the importer maps true cross-attention MHA here). Consumes MULTIPLE graph
    inputs via the graph's multi-input layer protocol; inputs arrive in Keras
    call order [query, value(, key)] (key defaults to value).

    Separate projections (Wq/Wk/Wv) rather than the fused Wqkv of
    SelfAttentionLayer, because the sources (and ``value_size``) may differ.
    """

    n_in: int = 0          # query feature dim
    k_in: int = 0          # key source feature dim
    v_in: int = 0          # value source feature dim
    n_out: int = 0         # output dim (default: query dim)
    n_heads: int = 1
    head_size: Optional[int] = None   # Dh for q/k
    value_size: Optional[int] = None  # Dv (defaults to head_size)
    attn_dropout: float = 0.0

    consumes_multiple_inputs = True

    def _dh(self) -> int:
        return self.head_size or max(1, self.n_in // self.n_heads)

    def _dv(self) -> int:
        return self.value_size or self._dh()

    def set_n_in_multi(self, input_types) -> None:
        if not self.n_in:
            self.n_in = input_types[0].size
        if len(input_types) > 1 and not self.v_in:
            self.v_in = input_types[1].size
        if not self.k_in:
            self.k_in = (input_types[2].size if len(input_types) > 2
                         else self.v_in or self.n_in)
        if not self.v_in:
            self.v_in = self.n_in
        if not self.n_out:
            self.n_out = self.n_in

    def output_type_multi(self, input_types) -> InputType:
        return InputType.recurrent(self.n_out or input_types[0].size,
                                   input_types[0].timesteps)

    def param_shapes(self):
        h, dh, dv = self.n_heads, self._dh(), self._dv()
        return {"Wq": (self.n_in, h * dh), "bq": (h * dh,),
                "Wk": (self.k_in, h * dh), "bk": (h * dh,),
                "Wv": (self.v_in, h * dv), "bv": (h * dv,),
                "Wo": (h * dv, self.n_out), "bo": (self.n_out,)}

    def init_params(self, rng, dtype=jnp.float32):
        out = {}
        keys = jax.random.split(rng, 4)
        shapes = self.param_shapes()
        for k, name in zip(keys, ("Wq", "Wk", "Wv", "Wo")):
            s = shapes[name]
            out[name] = self._init_w(k, s, s[0], s[1], dtype)
            out["b" + name[1:].lower()] = jnp.zeros(shapes["b" + name[1:].lower()], dtype)
        return out

    def forward_multi(self, params, inputs, *, state=None, train=False,
                      rng=None, masks=None):
        xq = inputs[0]
        xv = inputs[1] if len(inputs) > 1 else xq
        xk = inputs[2] if len(inputs) > 2 else xv
        n, tq, _ = xq.shape
        tk = xk.shape[1]
        h, dh, dv = self.n_heads, self._dh(), self._dv()
        q = (xq @ params["Wq"] + params["bq"]).reshape(n, tq, h, dh).transpose(0, 2, 1, 3)
        k = (xk @ params["Wk"] + params["bk"]).reshape(n, tk, h, dh).transpose(0, 2, 1, 3)
        v = (xv @ params["Wv"] + params["bv"]).reshape(n, tk, h, dv).transpose(0, 2, 1, 3)
        kv_mask = None
        if masks is not None:
            # mask over KEYS: the key source's mask, falling back to the
            # value source's; in the single-input (self-attention) case the
            # query mask IS the key mask
            kv_mask = masks[2] if len(masks) > 2 and masks[2] is not None \
                else (masks[1] if len(masks) > 1 else
                      (masks[0] if masks else None))
        out = dot_product_attention(q, k, v, mask=kv_mask,
                                    dropout_rate=self.attn_dropout,
                                    rng=rng, train=train)
        out = out.transpose(0, 2, 1, 3).reshape(n, tq, h * dv)
        y = out @ params["Wo"] + params["bo"]
        return self.act_fn()(y), state or {}

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        # single-input degenerate case == self-attention over x (the mask
        # applies to the keys, which are x itself)
        return self.forward_multi(params, [x], state=state, train=train,
                                  rng=rng,
                                  masks=None if mask is None else [mask])
