"""Variational Autoencoder layer.

Reference: ``nn/conf/layers/variational/VariationalAutoencoder.java`` + its
own Layer impl (``nn/layers/variational/VariationalAutoencoder.java:51``) with
pluggable reconstruction distributions (Bernoulli / Gaussian / Exponential).
Forward in a network = encoder mean (matching DL4J's ``activate`` =
``preOutput`` of the mean); ``pretrain_loss`` is the negative ELBO with the
reparameterization trick (``jax.grad`` replaces the hand-derived gradients).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as act_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass
class VariationalAutoencoderLayer(Layer):
    n_in: int = 0
    n_out: int = 0  # latent size
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    reconstruction_distribution: str = "bernoulli"  # "bernoulli" | "gaussian"
    pzx_activation: str = "identity"
    num_samples: int = 1

    def __post_init__(self):
        if self.activation is None:
            self.activation = "leakyrelu"
        if isinstance(self.encoder_layer_sizes, list):
            self.encoder_layer_sizes = tuple(self.encoder_layer_sizes)
        if isinstance(self.decoder_layer_sizes, list):
            self.decoder_layer_sizes = tuple(self.decoder_layer_sizes)

    def is_pretrain_layer(self) -> bool:
        return True

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _recon_out_size(self):
        # gaussian reconstruction emits mean+logvar per input dim
        return self.n_in * 2 if self.reconstruction_distribution == "gaussian" else self.n_in

    def param_shapes(self):
        shapes = {}
        prev = self.n_in
        for i, h in enumerate(self.encoder_layer_sizes):
            shapes[f"eW{i}"] = (prev, h)
            shapes[f"eb{i}"] = (h,)
            prev = h
        shapes["pZXMeanW"] = (prev, self.n_out)
        shapes["pZXMeanb"] = (self.n_out,)
        shapes["pZXLogStd2W"] = (prev, self.n_out)
        shapes["pZXLogStd2b"] = (self.n_out,)
        prev = self.n_out
        for i, h in enumerate(self.decoder_layer_sizes):
            shapes[f"dW{i}"] = (prev, h)
            shapes[f"db{i}"] = (h,)
            prev = h
        shapes["pXZW"] = (prev, self._recon_out_size())
        shapes["pXZb"] = (self._recon_out_size(),)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        shapes = self.param_shapes()
        keys = jax.random.split(rng, len(shapes))
        params = {}
        for (name, shape), k in zip(shapes.items(), keys):
            if name.endswith("b") and len(shape) == 1:
                params[name] = jnp.zeros(shape, dtype)
            else:
                params[name] = self._init_w(k, shape, shape[0], shape[-1], dtype)
        return params

    def _encode(self, params, x):
        act = self.act_fn()
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        pzx_act = act_mod.resolve(self.pzx_activation)
        mean = pzx_act(h @ params["pZXMeanW"] + params["pZXMeanb"])
        log_var = h @ params["pZXLogStd2W"] + params["pZXLogStd2b"]
        return mean, log_var

    def _decode(self, params, z):
        act = self.act_fn()
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["pXZW"] + params["pXZb"]

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        mean, _ = self._encode(params, x)
        return mean, state or {}

    def generate(self, params, z):
        """Decode latent samples to reconstruction-distribution means."""
        logits = self._decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(logits)
        mean, _ = jnp.split(logits, 2, axis=-1)
        return mean

    def reconstruction_log_prob(self, params, x, z):
        logits = self._decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            lp = -(jnp.maximum(logits, 0) - logits * x + jnp.log1p(jnp.exp(-jnp.abs(logits))))
            return jnp.sum(lp, axis=-1)
        mean, log_var = jnp.split(logits, 2, axis=-1)
        lp = -0.5 * (jnp.log(2 * jnp.pi) + log_var + (x - mean) ** 2 / jnp.exp(log_var))
        return jnp.sum(lp, axis=-1)

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO (mean over batch)."""
        mean, log_var = self._encode(params, x)
        total = 0.0
        keys = jax.random.split(rng, self.num_samples) if rng is not None else [None]
        for k in keys[:self.num_samples]:
            eps = jax.random.normal(k, mean.shape, mean.dtype) if k is not None else 0.0
            z = mean + jnp.exp(0.5 * log_var) * eps
            total = total + jnp.mean(self.reconstruction_log_prob(params, x, z))
        recon = total / self.num_samples
        kl = -0.5 * jnp.sum(1 + log_var - mean**2 - jnp.exp(log_var), axis=-1)
        return jnp.mean(kl) - recon
