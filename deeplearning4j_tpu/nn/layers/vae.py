"""Variational Autoencoder layer.

Reference: ``nn/conf/layers/variational/VariationalAutoencoder.java`` + its
own Layer impl (``nn/layers/variational/VariationalAutoencoder.java:51``) with
the full pluggable reconstruction-distribution family (Bernoulli / Gaussian /
Exponential / Composite / LossFunctionWrapper — see ``vae_distributions.py``).
Forward in a network = encoder mean (matching DL4J's ``activate`` =
``preOutput`` of the mean); ``pretrain_loss`` is the negative ELBO with the
reparameterization trick (``jax.grad`` replaces the hand-derived gradients).
``reconstruction_log_probability`` implements the reference's Monte-Carlo
estimator (``VariationalAutoencoder.java:998``); ``reconstruction_error`` the
LossFunctionWrapper path (``:1146``).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as act_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.layers.vae_distributions import (
    ReconstructionDistribution,
    resolve_reconstruction,
)


@register_layer
@dataclasses.dataclass
class VariationalAutoencoderLayer(Layer):
    n_in: int = 0
    n_out: int = 0  # latent size
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    # "bernoulli" | "gaussian" | "exponential" shorthand, or any
    # ReconstructionDistribution instance (Composite, LossFunctionWrapper, …)
    reconstruction_distribution: object = "bernoulli"
    pzx_activation: str = "identity"
    num_samples: int = 1

    def __post_init__(self):
        if self.activation is None:
            self.activation = "leakyrelu"
        if isinstance(self.encoder_layer_sizes, list):
            self.encoder_layer_sizes = tuple(self.encoder_layer_sizes)
        if isinstance(self.decoder_layer_sizes, list):
            self.decoder_layer_sizes = tuple(self.decoder_layer_sizes)

    @property
    def recon(self) -> ReconstructionDistribution:
        return resolve_reconstruction(self.reconstruction_distribution)

    def has_loss_function(self) -> bool:
        """True when reconstruction uses a plain loss (LossFunctionWrapper /
        all-loss Composite) instead of a probability distribution."""
        return self.recon.has_loss_function()

    def is_pretrain_layer(self) -> bool:
        return True

    def set_n_in(self, input_type: InputType) -> None:
        if not self.n_in:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _recon_out_size(self):
        return self.recon.distribution_input_size(self.n_in)

    def param_shapes(self):
        shapes = {}
        prev = self.n_in
        for i, h in enumerate(self.encoder_layer_sizes):
            shapes[f"eW{i}"] = (prev, h)
            shapes[f"eb{i}"] = (h,)
            prev = h
        shapes["pZXMeanW"] = (prev, self.n_out)
        shapes["pZXMeanb"] = (self.n_out,)
        shapes["pZXLogStd2W"] = (prev, self.n_out)
        shapes["pZXLogStd2b"] = (self.n_out,)
        prev = self.n_out
        for i, h in enumerate(self.decoder_layer_sizes):
            shapes[f"dW{i}"] = (prev, h)
            shapes[f"db{i}"] = (h,)
            prev = h
        shapes["pXZW"] = (prev, self._recon_out_size())
        shapes["pXZb"] = (self._recon_out_size(),)
        return shapes

    def init_params(self, rng, dtype=jnp.float32):
        shapes = self.param_shapes()
        keys = jax.random.split(rng, len(shapes))
        params = {}
        for (name, shape), k in zip(shapes.items(), keys):
            if name.endswith("b") and len(shape) == 1:
                params[name] = jnp.zeros(shape, dtype)
            else:
                params[name] = self._init_w(k, shape, shape[0], shape[-1], dtype)
        return params

    def _encode(self, params, x):
        act = self.act_fn()
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        pzx_act = act_mod.resolve(self.pzx_activation)
        mean = pzx_act(h @ params["pZXMeanW"] + params["pZXMeanb"])
        log_var = pzx_act(h @ params["pZXLogStd2W"] + params["pZXLogStd2b"])
        return mean, log_var

    def _decode(self, params, z):
        act = self.act_fn()
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["pXZW"] + params["pXZb"]

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        mean, _ = self._encode(params, x)
        return mean, state or {}

    def generate(self, params, z):
        """Decode latent values to E[P(x|z)] (generateAtMeanGivenZ)."""
        return self.recon.generate_at_mean(self._decode(params, z))

    def generate_random(self, params, z, rng):
        """Decode latent values and SAMPLE P(x|z) (generateRandomGivenZ)."""
        return self.recon.generate_random(rng, self._decode(params, z))

    def reconstruction_log_prob(self, params, x, z):
        """Per-example log p(x|z) (negated distribution cost)."""
        return -self.recon.example_neg_log_prob(x, self._decode(params, z))

    def reconstruction_log_probability(self, params, x, rng,
                                       num_samples: int = None):
        """Monte-Carlo estimate of per-example log p(x): the mean over
        ``num_samples`` posterior draws of log p(x|z), z ~ q(z|x)
        (``VariationalAutoencoder.java:998``). Returns shape [N]."""
        if self.has_loss_function():
            raise ValueError(
                "Cannot calculate reconstruction log probability when using "
                "a LossFunctionWrapper: loss functions are not probabilistic. "
                "Use reconstruction_error instead")
        k = num_samples if num_samples is not None else self.num_samples
        if k <= 0:
            raise ValueError(f"num_samples must be > 0, got {k}")
        mean, log_var = self._encode(params, x)
        sigma = jnp.exp(0.5 * log_var)
        total = 0.0
        for key in jax.random.split(rng, k):
            z = mean + sigma * jax.random.normal(key, mean.shape, mean.dtype)
            total = total + self.reconstruction_log_prob(params, x, z)
        return total / k

    def reconstruction_probability(self, params, x, rng,
                                   num_samples: int = None):
        """exp of :meth:`reconstruction_log_probability` (``:985``)."""
        return jnp.exp(self.reconstruction_log_probability(
            params, x, rng, num_samples))

    def reconstruction_error(self, params, x):
        """Per-example deterministic reconstruction error — only for
        loss-function reconstruction configs (``:1146``)."""
        if not self.has_loss_function():
            raise ValueError(
                "reconstruction_error requires a loss-function configuration "
                "(LossFunctionWrapper / all-loss Composite); probabilistic "
                "distributions use reconstruction_log_probability")
        mean, _ = self._encode(params, x)
        reconstruction = self.generate(params, mean)
        return self.recon.score_array(x, reconstruction)

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO (mean over batch)."""
        mean, log_var = self._encode(params, x)
        total = 0.0
        keys = jax.random.split(rng, self.num_samples) if rng is not None else [None]
        for k in keys[:self.num_samples]:
            eps = jax.random.normal(k, mean.shape, mean.dtype) if k is not None else 0.0
            z = mean + jnp.exp(0.5 * log_var) * eps
            total = total + jnp.mean(self.reconstruction_log_prob(params, x, z))
        recon = total / self.num_samples
        kl = -0.5 * jnp.sum(1 + log_var - mean**2 - jnp.exp(log_var), axis=-1)
        return jnp.mean(kl) - recon
