"""Layer wrappers: FrozenLayer.

Reference: ``nn/conf/layers/misc/FrozenLayer.java`` — wraps a layer so its
params are excluded from training (used by TransferLearning). Implemented
with ``lax.stop_gradient`` on the wrapped params: gradients are exactly zero,
and the updater never moves them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass
class FrozenLayer(Layer):
    layer: Optional[Layer] = None

    def set_n_in(self, input_type: InputType) -> None:
        self.layer.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return self.layer.output_type(input_type)

    def input_preprocessor(self, input_type: InputType):
        return self.layer.input_preprocessor(input_type)

    def apply_global_defaults(self, g):
        # frozen layers do NOT inherit training hyperparams; the inner layer
        # keeps whatever it was configured with
        if self.layer is not None:
            self.layer.apply_global_defaults(g)

    def param_shapes(self):
        return self.layer.param_shapes()

    def init_params(self, rng, dtype=None):
        import jax.numpy as jnp
        return self.layer.init_params(rng, dtype or jnp.float32)

    def init_state(self):
        return self.layer.init_state()

    def has_loss(self):
        return self.layer.has_loss()

    def compute_loss(self, params, x, labels, mask=None):
        return self.layer.compute_loss(lax.stop_gradient(params), x, labels, mask)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        frozen = jax.tree_util.tree_map(lax.stop_gradient, params)
        return self.layer.forward(frozen, x, state=state, train=train, rng=rng, mask=mask)


@register_layer
@dataclasses.dataclass
class TimeDistributedWrapper(Layer):
    """Applies the wrapped layer independently at every timestep by folding
    time into batch: [N, T, ...] → [N*T, ...] → inner → [N, T, ...].

    Keras ``TimeDistributed`` semantics for non-position-wise inner layers
    (Conv2D, pooling over image sequences); position-wise layers (Dense etc.)
    broadcast over leading dims natively and never need this wrapper. The
    reshape is free under XLA (layout no-op), so the inner conv runs as one
    big batched conv on the MXU.
    """

    layer: Optional[Layer] = None

    @staticmethod
    def _inner_type(input_type: InputType) -> InputType:
        if input_type.kind == "cnn_seq":
            return InputType.convolutional(input_type.height, input_type.width,
                                           input_type.channels)
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        raise ValueError(
            f"TimeDistributed expects sequence input, got {input_type.kind!r}")

    def set_n_in(self, input_type: InputType) -> None:
        self.layer.set_n_in(self._inner_type(input_type))

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(self._inner_type(input_type))
        t = input_type.timesteps
        if inner.kind == "cnn":
            return InputType.recurrent_convolutional(inner.height, inner.width,
                                                     inner.channels, t)
        return InputType.recurrent(inner.flat_size(), t)

    def apply_global_defaults(self, g):
        super().apply_global_defaults(g)
        if self.layer is not None:
            self.layer.apply_global_defaults(g)

    def param_shapes(self):
        return self.layer.param_shapes()

    def init_params(self, rng, dtype=None):
        import jax.numpy as jnp
        return self.layer.init_params(rng, dtype or jnp.float32)

    def init_state(self):
        return self.layer.init_state()

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        n, t = x.shape[:2]
        flat = x.reshape((n * t,) + x.shape[2:])
        y, new_state = self.layer.forward(params, flat, state=state,
                                          train=train, rng=rng)
        return y.reshape((n, t) + y.shape[1:]), new_state
