"""Layer wrappers: FrozenLayer.

Reference: ``nn/conf/layers/misc/FrozenLayer.java`` — wraps a layer so its
params are excluded from training (used by TransferLearning). Implemented
with ``lax.stop_gradient`` on the wrapped params: gradients are exactly zero,
and the updater never moves them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, register_layer


@register_layer
@dataclasses.dataclass
class FrozenLayer(Layer):
    layer: Optional[Layer] = None

    def set_n_in(self, input_type: InputType) -> None:
        self.layer.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return self.layer.output_type(input_type)

    def input_preprocessor(self, input_type: InputType):
        return self.layer.input_preprocessor(input_type)

    def apply_global_defaults(self, g):
        # frozen layers do NOT inherit training hyperparams; the inner layer
        # keeps whatever it was configured with
        if self.layer is not None:
            self.layer.apply_global_defaults(g)

    def param_shapes(self):
        return self.layer.param_shapes()

    def init_params(self, rng, dtype=None):
        import jax.numpy as jnp
        return self.layer.init_params(rng, dtype or jnp.float32)

    def init_state(self):
        return self.layer.init_state()

    def has_loss(self):
        return self.layer.has_loss()

    def compute_loss(self, params, x, labels, mask=None):
        return self.layer.compute_loss(lax.stop_gradient(params), x, labels, mask)

    def forward(self, params, x, *, state=None, train=False, rng=None, mask=None):
        frozen = jax.tree_util.tree_map(lax.stop_gradient, params)
        return self.layer.forward(frozen, x, state=state, train=train, rng=rng, mask=mask)
