"""Loss functions with ND4J ``ILossFunction`` parity.

Reference: DL4J layer configs carry an ``ILossFunction`` (e.g.
``nn/conf/layers/OutputLayer`` via ``BaseOutputLayer``); the ND4J loss
implementations (LossMCXENT, LossMSE, LossBinaryXENT, …) compute per-example
scores with optional per-output weights and per-example/per-timestep masks.

Design: every loss is ``loss(labels, preactivation_or_probs, mask=None,
weights=None) -> scalar mean score``; losses that fuse with their canonical
activation (softmax+MCXENT, sigmoid+XENT) are computed from *logits* for
numerical stability — the framework passes logits when the output layer's
activation matches the canonical pairing, mirroring how ND4J special-cases
softmax in ``LossMCXENT``.

Masks broadcast like DL4J's: shape [N] or [N, T] (per example / per timestep)
or full label shape.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

Array = jax.Array
_EPS = 1e-7


def _apply_mask_mean(per_elem: Array, mask: Optional[Array]) -> Array:
    """Mean of per-example scores, honouring a broadcastable mask.

    ``per_elem`` has shape [N] or [N, T] (already reduced over features).
    DL4J averages the summed score over the number of *unmasked examples*
    (see BaseOutputLayer.computeScore: score / getInputMiniBatchSize, with
    masked timesteps contributing zero).
    """
    if mask is None:
        return jnp.mean(per_elem)
    mask = jnp.broadcast_to(mask.astype(per_elem.dtype), per_elem.shape)
    total = jnp.sum(per_elem * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def _featurewise(per_out: Array, weights: Optional[Array]) -> Array:
    """Apply per-output weights then reduce feature axis → per-example score."""
    if weights is not None:
        per_out = per_out * weights
    return jnp.sum(per_out, axis=-1)


def mse(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    # DL4J LossMSE = LossL2 / nOut (mean over outputs)
    per = _featurewise((preds - labels) ** 2, weights) / labels.shape[-1]
    return _apply_mask_mean(per, mask)


def l2(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise((preds - labels) ** 2, weights)
    return _apply_mask_mean(per, mask)


def l1(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(jnp.abs(preds - labels), weights)
    return _apply_mask_mean(per, mask)


def mae(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(jnp.abs(preds - labels), weights) / labels.shape[-1]
    return _apply_mask_mean(per, mask)


def mape(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(
        jnp.abs((preds - labels) / jnp.where(jnp.abs(labels) < _EPS, _EPS, labels)),
        weights,
    ) * (100.0 / labels.shape[-1])
    return _apply_mask_mean(per, mask)


def msle(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(
        (jnp.log1p(jnp.maximum(preds, -1 + _EPS)) - jnp.log1p(jnp.maximum(labels, -1 + _EPS))) ** 2,
        weights,
    ) / labels.shape[-1]
    return _apply_mask_mean(per, mask)


def mcxent_logits(labels: Array, logits: Array, mask=None, weights=None) -> Array:
    """Multi-class cross entropy fused with softmax (stable)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -_featurewise(labels * logp, weights)
    return _apply_mask_mean(per, mask)


def mcxent_probs(labels: Array, probs: Array, mask=None, weights=None) -> Array:
    per = -_featurewise(labels * jnp.log(jnp.clip(probs, _EPS, 1.0)), weights)
    return _apply_mask_mean(per, mask)


def sparse_mcxent_logits(labels: Array, logits: Array, mask=None, weights=None) -> Array:
    """Labels are integer class indices, not one-hot."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if weights is not None:
        per = per * jnp.take(weights, labels.astype(jnp.int32))
    return _apply_mask_mean(per, mask)


def xent_logits(labels: Array, logits: Array, mask=None, weights=None) -> Array:
    """Binary cross entropy fused with sigmoid (stable)."""
    per_out = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    per = _featurewise(per_out, weights)
    return _apply_mask_mean(per, mask)


def xent_probs(labels: Array, probs: Array, mask=None, weights=None) -> Array:
    p = jnp.clip(probs, _EPS, 1.0 - _EPS)
    per = -_featurewise(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p), weights)
    return _apply_mask_mean(per, mask)


def negativeloglikelihood_logits(labels, logits, mask=None, weights=None) -> Array:
    # DL4J LossNegativeLogLikelihood extends LossMCXENT (same math when
    # paired with softmax).
    return mcxent_logits(labels, logits, mask, weights)


def hinge(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    # labels in {-1, +1}
    per = _featurewise(jnp.maximum(0.0, 1.0 - labels * preds), weights)
    return _apply_mask_mean(per, mask)


def squared_hinge(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(jnp.maximum(0.0, 1.0 - labels * preds) ** 2, weights)
    return _apply_mask_mean(per, mask)


def kl_divergence(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    lab = jnp.clip(labels, _EPS, 1.0)
    prd = jnp.clip(preds, _EPS, 1.0)
    per = _featurewise(lab * (jnp.log(lab) - jnp.log(prd)), weights)
    return _apply_mask_mean(per, mask)


def poisson(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(preds - labels * jnp.log(jnp.clip(preds, _EPS, None)), weights)
    return _apply_mask_mean(per, mask)


def cosine_proximity(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    ln = jnp.linalg.norm(labels, axis=-1)
    pn = jnp.linalg.norm(preds, axis=-1)
    dot = jnp.sum(labels * preds, axis=-1)
    per = -dot / jnp.maximum(ln * pn, _EPS)
    return _apply_mask_mean(per, mask)


def wasserstein(labels: Array, preds: Array, mask=None, weights=None) -> Array:
    per = _featurewise(labels * preds, weights)
    return _apply_mask_mean(per, mask)


LossFn = Callable[..., Array]

# name -> (loss_from_canonical_input, fused_activation or None)
# When fused_activation matches the output layer's activation, the framework
# calls the loss with raw logits; otherwise with activated outputs.
_REGISTRY: dict[str, tuple[LossFn, Optional[str]]] = {
    "mse": (mse, None),
    "l2": (l2, None),
    "l1": (l1, None),
    "mae": (mae, None),
    "mean_absolute_error": (mae, None),
    "mean_squared_logarithmic_error": (msle, None),
    "msle": (msle, None),
    "mape": (mape, None),
    "mean_absolute_percentage_error": (mape, None),
    "mcxent": (mcxent_logits, "softmax"),
    "negativeloglikelihood": (negativeloglikelihood_logits, "softmax"),
    "sparse_mcxent": (sparse_mcxent_logits, "softmax"),
    "xent": (xent_logits, "sigmoid"),
    "binary_xent": (xent_logits, "sigmoid"),
    "hinge": (hinge, None),
    "squared_hinge": (squared_hinge, None),
    "kl_divergence": (kl_divergence, None),
    "reconstruction_crossentropy": (xent_probs, None),
    "poisson": (poisson, None),
    "cosine_proximity": (cosine_proximity, None),
    "wasserstein": (wasserstein, None),
}

# probability-space fallbacks for fused losses when the output activation does
# NOT match the canonical pairing (e.g. MCXENT with sigmoid outputs).
_PROB_SPACE: dict[str, LossFn] = {
    "mcxent": mcxent_probs,
    "negativeloglikelihood": mcxent_probs,
    "xent": xent_probs,
    "binary_xent": xent_probs,
}


def names() -> list[str]:
    return sorted(_REGISTRY)


def resolve(loss: Union[str, LossFn], activation: Optional[str] = None):
    """Resolve a loss spec to ``(fn, wants_logits: bool)``.

    ``wants_logits`` is True when ``fn`` should be fed the *pre-activation*
    output of the final layer (fused stable path), which happens when the loss
    has a canonical activation equal to ``activation``.
    """
    if callable(loss):
        return loss, False
    key = loss.lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss {loss!r}; known: {names()}")
    fn, fused_act = _REGISTRY[key]
    if fused_act is not None:
        if activation is None or activation.lower() == fused_act:
            return fn, True
        return _PROB_SPACE[key], False
    return fn, False
