"""Device-resident training tick: (iteration, epoch, rng key) carried
through the donated train step.

The naive fit loop performs a host-side ``jax.random.split`` plus two scalar
``jnp.asarray`` placements per step — three extra device dispatches that a
locally-attached chip absorbs but a remote dispatch link bills at full price
(measured: 14 ms/step of the ResNet50 headline, round 3). Instead the jitted
step splits the key ON DEVICE and returns ``(it + 1, next_key)``; the fit
loop re-feeds them with zero additional host-side device ops. The host keeps
plain-int mirrors for listeners; any external mutation of
``model.iteration`` / ``model.epoch`` (restore, manual reset, epoch
boundary) invalidates the cached tick via mirror mismatch and a fresh one is
placed from host state.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

_TLS = threading.local()


@contextlib.contextmanager
def schedule_tick(it, ep):
    """Make ``(iteration, epoch)`` visible to schedule-bearing configs
    (dropout ``pSchedule`` — ``conf/dropout/Dropout.java:45,68``) while the
    train step traces. The values are the step's device tracers, so a
    scheduled retain probability compiles INTO the step instead of
    fragmenting it — the reason schedules were rejected before the device
    tick existed. Thread-local: safe under ParallelWrapper's worker
    threads."""
    prev = getattr(_TLS, "tick", None)
    _TLS.tick = (it, ep)
    try:
        yield
    finally:
        _TLS.tick = prev


def current_schedule_tick():
    """(iteration, epoch) of the train step being traced, or ``(0, 0)``
    outside one (a scheduled value then evaluates at its initial point —
    e.g. a probe forward before training starts)."""
    t = getattr(_TLS, "tick", None)
    return t if t is not None else (0.0, 0.0)


def device_tick(model):
    """(it, ep, rng) device arrays for the next step — cached while the
    host-side mirrors are unchanged."""
    mirror = (model.iteration, model.epoch)
    cached = getattr(model, "_tick", None)
    if cached is not None and cached[0] == mirror:
        return cached[1]
    it = jnp.asarray(float(model.iteration), jnp.float32)
    ep = jnp.asarray(float(model.epoch), jnp.float32)
    rng = model._next_rng()
    model._tick = (mirror, (it, ep, rng))
    return it, ep, rng


def store_tick(model, new_it, new_rng) -> None:
    """Adopt the step's returned (it+1, next_key); call AFTER incrementing
    ``model.iteration`` so the mirror matches."""
    cached = getattr(model, "_tick", None)
    if cached is None:
        return
    _, (_, ep, _) = cached
    model._tick = ((model.iteration, model.epoch), (new_it, ep, new_rng))
