"""Simple classification result wrappers.

Parity with ``nn/simple/`` — ``multiclass/RankClassificationResult.java``
(per-row class rankings over a probability matrix) and
``binary/BinaryClassificationResult.java`` (decision threshold + class
weights holder).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["RankClassificationResult", "BinaryClassificationResult"]


class RankClassificationResult:
    """Ranked class outcomes per example (``RankClassificationResult``).

    ``outcome``: [N, C] probabilities (a single vector is treated as one
    row). Classes are ranked descending per row.
    """

    def __init__(self, outcome, labels: Optional[Sequence[str]] = None):
        out = np.asarray(outcome, np.float32)
        if out.ndim == 1:
            out = out[None, :]
        if out.ndim > 2:
            raise ValueError(
                "Only works with vectors and matrices right now")
        self.probabilities = out
        n_classes = out.shape[1]
        if labels is None:
            self.labels = [str(i) for i in range(n_classes)]
        else:
            if len(labels) != n_classes:
                raise ValueError(
                    f"{len(labels)} labels for {n_classes} classes")
            self.labels = list(labels)
        # descending probability order per row
        self.ranked_indices = np.argsort(-out, axis=1)

    def max_outcome_for_row(self, r: int) -> str:
        """Top label of row ``r`` (``maxOutcomeForRow``)."""
        return self.labels[int(self.ranked_indices[r, 0])]

    def max_outcomes(self) -> List[str]:
        """Top label per row (``maxOutcomes``)."""
        return [self.max_outcome_for_row(r)
                for r in range(self.ranked_indices.shape[0])]

    def ranked_labels_for_row(self, r: int) -> List[str]:
        """All labels of row ``r``, best first."""
        return [self.labels[int(i)] for i in self.ranked_indices[r]]

    def probability_for_row(self, r: int, cls: int) -> float:
        return float(self.probabilities[r, cls])


@dataclasses.dataclass
class BinaryClassificationResult:
    """Decision threshold + class weights
    (``BinaryClassificationResult.java``)."""

    decision_threshold: float = 0.5
    class_weights: Optional[Sequence[float]] = None

    def decide(self, probabilities) -> np.ndarray:
        """Thresholded positive-class decisions for [N] or [N,2] input."""
        p = np.asarray(probabilities, np.float64)
        if p.ndim == 2:
            p = p[:, -1]
        if self.class_weights is not None and len(self.class_weights) == 2:
            w0, w1 = self.class_weights
            p = p * w1 / np.maximum(p * w1 + (1 - p) * w0, 1e-12)
        return (p >= self.decision_threshold).astype(np.int64)
