"""Pallas TPU kernels — the opt-in fused implementations behind the helper
seam (the cuDNN role, `CudnnLSTMHelper.java:49` / ``cudnnRNNForwardTraining``).

``PallasLSTMHelper`` fuses the whole LSTM recurrence into ONE kernel launch:
the input projection is precomputed as a single MXU matmul outside, then a
sequential grid over time keeps h/c in VMEM scratch across steps — recurrent
matmul + all four gate activations + state update stay in VMEM.
Differentiation is handled with ``jax.custom_vjp``: the backward pass reuses
the reference scan implementation's VJP, so the helper is safe under
``jax.grad``.

Measured on TPU v5e (2x512 LSTM, B=64, T=128, f32): the fused kernel matches
stock XLA scan inference within noise (~6 ms/call both, bit-identical
outputs) — XLA already keeps this recurrence's carry on-chip at these sizes.
The helper seam's value is the cuDNN-parity architecture: an opt-in kernel
slot per layer family, validated by same-math equivalence tests, ready for
shapes/fusions where the compiler does leave perf on the table. (The win
that did generalize — hoisting the input projection out of the scan — lives
in the default path in ``layers/recurrent.py`` and is helper-independent:
1.62x on LSTM training.)
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.nn.helpers import (AttentionHelper, LSTMHelper,
                                            UpdaterHelper)


def _lstm_kernel(hidden: int, t_total: int,
                 xw_ref, rw_ref, h0_ref, c0_ref,
                 ys_ref, hn_ref, cn_ref, h_scr, c_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    z = xw_ref[0] + jnp.dot(h_scr[:], rw_ref[:],
                            preferred_element_type=jnp.float32).astype(xw_ref.dtype)
    H = hidden
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H])
    o = jax.nn.sigmoid(z[:, 2 * H:3 * H])
    g = jnp.tanh(z[:, 3 * H:])
    c = f * c_scr[:] + i * g
    h = o * jnp.tanh(c)
    h_scr[:] = h
    c_scr[:] = c
    ys_ref[0] = h

    @pl.when(t == t_total - 1)
    def _final():
        hn_ref[:] = h
        cn_ref[:] = c


def _lstm_pallas_fwd(xw, rw, h0, c0, *, interpret: bool):
    """xw [T,N,4H] (input projection + bias), rw [H,4H] → (ys [T,N,H], hN, cN)."""
    T, N, H4 = xw.shape
    H = H4 // 4
    grid = (T,)
    return pl.pallas_call(
        functools.partial(_lstm_kernel, H, T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, N, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, H), xw.dtype),
            jax.ShapeDtypeStruct((N, H), xw.dtype),
            jax.ShapeDtypeStruct((N, H), xw.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, H), xw.dtype),
            pltpu.VMEM((N, H), xw.dtype),
        ],
        interpret=interpret,
    )(xw, rw, h0, c0)


def _lstm_ref_scan(xw, rw, h0, c0):
    """Reference recurrence (identical math to LSTMLayer._cell_pre with
    sigmoid gates / tanh cell): supplies the VJP for the pallas forward."""
    H = rw.shape[0]

    def step(carry, xw_t):
        h_prev, c_prev = carry
        z = xw_t + h_prev @ rw
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H:2 * H])
        o = jax.nn.sigmoid(z[:, 2 * H:3 * H])
        g = jnp.tanh(z[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hn, cn), ys = jax.lax.scan(step, (h0, c0), xw)
    return ys, hn, cn


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_fused(xw, rw, h0, c0, interpret: bool = False):
    return _lstm_pallas_fwd(xw, rw, h0, c0, interpret=interpret)


def _fused_fwd(xw, rw, h0, c0, interpret):
    out = _lstm_pallas_fwd(xw, rw, h0, c0, interpret=interpret)
    return out, (xw, rw, h0, c0)


def _fused_bwd(interpret, res, cts):
    xw, rw, h0, c0 = res
    _, vjp = jax.vjp(_lstm_ref_scan, xw, rw, h0, c0)
    return vjp(tuple(cts))


lstm_fused.defvjp(_fused_fwd, _fused_bwd)


class PallasLSTMHelper(LSTMHelper):
    """Fused-LSTM helper: standard LSTM (sigmoid gates, tanh cell, no
    peepholes, no mask). ``interpret=True`` runs the kernel in the Pallas
    interpreter (CPU testing)."""

    def __init__(self, interpret: bool = None):
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret

    def supports(self, layer, mask) -> bool:
        return (mask is None
                and not getattr(layer, "peephole", False)
                and layer.gate_activation == "sigmoid"
                and layer.activation in ("tanh",))

    def forward_seq(self, layer, params, x, carry):
        n, t, _ = x.shape
        if carry is None:
            carry = layer.init_carry(n, x.dtype)
        h0, c0 = carry
        xw = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)  # [T,N,4H]
        rw = params["RW"][:, :4 * layer.n_out]
        ys, hn, cn = lstm_fused(xw, rw, h0, c0, self.interpret)
        return jnp.swapaxes(ys, 0, 1), (hn, cn)


# -- fused optimizer update ---------------------------------------------------
#
# One kernel launch per parameter tensor replaces the stock per-param
# elementwise chain (~10 XLA ops for Adam: two muls+adds for the moments, a
# sqrt, a divide, the bias-corrected step, the subtraction). param/m/v ride
# through ``input_output_aliases`` so the launch is a true in-place
# read-modify-write over the train step's donated buffers. The bias-correction
# scalars (which depend on the traced iteration count) are computed OUTSIDE
# the kernel — identical ops to the stock updater math — and arrive as one
# small SMEM coefficient row, so the kernel body is pure elementwise work on
# (rows, 128) f32 tiles.

_UPD_BLOCK_ROWS = 256  # (256, 128) f32 blocks: 128 KiB per operand in VMEM


def _adam_kernel(amsgrad: bool, coef_ref, *refs):
    # coef row: [beta1, beta2, eps, alpha, 0, 0] where
    # alpha = lr * sqrt(1 - beta2^t) / (1 - beta1^t) (precomputed outside)
    b1, b2, eps, alpha = (coef_ref[0, 0], coef_ref[0, 1], coef_ref[0, 2],
                          coef_ref[0, 3])
    if amsgrad:
        p_ref, m_ref, v_ref, vh_ref, g_ref, po, mo, vo, vho = refs
    else:
        p_ref, m_ref, v_ref, g_ref, po, mo, vo = refs
    g = g_ref[...]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    denom = v
    if amsgrad:
        denom = jnp.maximum(vh_ref[...], v)
        vho[...] = denom
    po[...] = p_ref[...] - alpha * m / (jnp.sqrt(denom) + eps)
    mo[...] = m
    vo[...] = v


def _nadam_kernel(coef_ref, p_ref, m_ref, v_ref, g_ref, po, mo, vo):
    # coef row: [beta1, beta2, eps, lr, 1-beta1^t, 1-beta2^t] — the kernel
    # divides by the same (1 - beta^t) denominators the stock path does, so
    # the math is op-for-op identical
    b1, b2, eps, lr = (coef_ref[0, 0], coef_ref[0, 1], coef_ref[0, 2],
                       coef_ref[0, 3])
    om1, om2 = coef_ref[0, 4], coef_ref[0, 5]
    g = g_ref[...]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    m_hat = m / om1
    v_hat = v / om2
    m_bar = b1 * m_hat + (1.0 - b1) * g / om1
    po[...] = p_ref[...] - lr * m_bar / (jnp.sqrt(v_hat) + eps)
    mo[...] = m
    vo[...] = v


def _fused_update_rows(kind: str, coef, bufs, *, interpret: bool):
    """Run the fused update on (R, 128) row-tiled operands.

    ``bufs`` = (p, m, v[, v_hat], g); returns the same tuple minus ``g``,
    updated. All state operands alias their outputs (in-place RMW)."""
    R = bufs[0].shape[0]
    block_r = min(_UPD_BLOCK_ROWS, R)
    grid = (R // block_r,)
    bs = lambda: pl.BlockSpec((block_r, 128), lambda i: (i, 0))  # noqa: E731
    n_state = len(bufs) - 1  # p/m/v(/v_hat) alias; g does not
    kernel = (_nadam_kernel if kind == "nadam"
              else functools.partial(_adam_kernel, kind == "amsgrad"))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
                 + [bs() for _ in bufs],
        out_specs=[bs() for _ in range(n_state)],
        out_shape=[jax.ShapeDtypeStruct((R, 128), bufs[0].dtype)
                   for _ in range(n_state)],
        input_output_aliases={1 + i: i for i in range(n_state)},
        interpret=interpret,
    )(coef, *bufs)


class PallasUpdaterHelper(UpdaterHelper):
    """Fused Adam/Nadam/AMSGrad update: new param + new moments in ONE
    kernel launch per parameter tensor, in place over donated buffers.
    Other updater classes (and non-f32 params) fall back to the stock XLA
    chain via ``supports``. ``interpret=True`` runs the kernel in the
    Pallas interpreter (CPU testing)."""

    def __init__(self, interpret: bool = None):
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret

    def supports(self, updater, param, grad) -> bool:
        from deeplearning4j_tpu.nn.updaters import Adam, AMSGrad, Nadam

        # exact types only: a subclass may override the math the kernel bakes
        if type(updater) not in (Adam, Nadam, AMSGrad):
            return False
        return (param.dtype == jnp.float32
                and getattr(grad, "shape", None) == param.shape
                and param.size > 0)

    @staticmethod
    def _rows(a, block_r):
        """Flatten + zero-pad to (R, 128) with R a multiple of ``block_r``.
        Zero padding is closed under the Adam-family math (moments stay 0,
        sqrt(0)+eps keeps the quotient finite), so padded lanes never
        contaminate real ones."""
        flat = a.reshape(-1)
        n = flat.shape[0]
        rows = -(-n // 128)
        r_pad = -(-rows // block_r) * block_r
        pad = r_pad * 128 - n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(r_pad, 128)

    def apply(self, updater, param, grad, state, lr, t):
        from deeplearning4j_tpu.nn.updaters import AMSGrad, Nadam

        f32 = jnp.float32
        b1 = jnp.asarray(updater.beta1, f32)
        b2 = jnp.asarray(updater.beta2, f32)
        eps = jnp.asarray(updater.epsilon, f32)
        lr = jnp.asarray(lr, f32)
        t = jnp.asarray(t, f32)
        om1 = 1.0 - updater.beta1 ** t  # same exponentiation as the stock path
        om2 = 1.0 - updater.beta2 ** t
        if isinstance(updater, Nadam):
            kind = "nadam"
            coef = jnp.stack([b1, b2, eps, lr, om1, om2])
            names = ("m", "v")
        else:
            kind = "amsgrad" if isinstance(updater, AMSGrad) else "adam"
            alpha = lr * jnp.sqrt(om2) / om1
            coef = jnp.stack([b1, b2, eps, alpha, jnp.zeros((), f32),
                              jnp.zeros((), f32)])
            names = ("m", "v", "v_hat") if kind == "amsgrad" else ("m", "v")

        rows = -(-param.size // 128)
        block_r = min(_UPD_BLOCK_ROWS, -(-rows // 8) * 8)  # f32 tile: 8 rows
        to_rows = lambda a: self._rows(a, block_r)  # noqa: E731
        bufs = ([to_rows(param)] + [to_rows(state[n]) for n in names]
                + [to_rows(grad.astype(param.dtype))])
        outs = _fused_update_rows(kind, coef.reshape(1, 6), tuple(bufs),
                                  interpret=self.interpret)
        unrows = lambda a: a.reshape(-1)[:param.size].reshape(param.shape)  # noqa: E731
        new_param = unrows(outs[0])
        new_state = {n: unrows(outs[1 + i]) for i, n in enumerate(names)}
        return new_param, new_state


class PallasFlashAttentionHelper(AttentionHelper):
    """Blockwise (flash) attention via the Pallas TPU kernel bundled with
    jax (`jax.experimental.pallas.ops.tpu.flash_attention`) — O(T) memory
    instead of materializing the [N,H,T,T] score matrix, with the module's
    own custom VJP for the backward.

    With the tuned 512-wide block sizes below (measured v5e, 8 heads, dh=64,
    forward): flash beats the einsum path 1.9x at T=8192 (15.9 vs 29.9 ms),
    1.1x at T=4096, and ties at T=1024-2048 — while keeping memory linear in
    T instead of the einsum path's O(T^2) score matrix. Default block sizes
    were 2.5x worse than tuned at T=8192; re-measure per TPU generation.

    Conservative support gate: TPU backend, no mask, no attention dropout,
    sequence length a multiple of 128, head dim in {64, 128, 256} (the tile
    shapes the kernel is built for); everything else falls back to the
    built-in einsum attention.
    """

    def __init__(self, causal: bool = False):
        self.causal = causal

    def supports(self, layer, q_shape, mask, dropout_active,
                 causal=False) -> bool:
        if jax.default_backend() != "tpu":
            return False
        if causal != self.causal:
            # semantics must match the request exactly: a causal kernel must
            # not serve a bidirectional layer and vice versa
            return False
        if mask is not None or dropout_active:
            return False
        t, dh = q_shape[-2], q_shape[-1]
        return t % 128 == 0 and dh in (64, 128, 256)

    @staticmethod
    def _block_sizes(t: int):
        from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

        b = next(c for c in (512, 256, 128) if t % c == 0)
        return BlockSizes(
            block_q=b, block_k_major=b, block_k=b, block_b=1,
            block_q_major_dkv=b, block_k_major_dkv=b, block_k_dkv=b,
            block_q_dkv=b, block_k_major_dq=b, block_k_dq=b, block_q_dq=b)

    def attend(self, q, k, v):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention)

        scale = float(1.0 / (q.shape[-1] ** 0.5))
        return flash_attention(q, k, v, causal=self.causal, sm_scale=scale,
                               block_sizes=self._block_sizes(q.shape[-2]))
