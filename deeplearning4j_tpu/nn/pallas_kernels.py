"""Pallas TPU kernels — the opt-in fused implementations behind the helper
seam (the cuDNN role, `CudnnLSTMHelper.java:49` / ``cudnnRNNForwardTraining``).

``PallasLSTMHelper`` fuses the whole LSTM recurrence into ONE kernel launch:
the input projection is precomputed as a single MXU matmul outside, then a
sequential grid over time keeps h/c in VMEM scratch across steps — recurrent
matmul + all four gate activations + state update stay in VMEM.
Differentiation is handled with ``jax.custom_vjp``: the backward pass reuses
the reference scan implementation's VJP, so the helper is safe under
``jax.grad``.

Measured on TPU v5e (2x512 LSTM, B=64, T=128, f32): the fused kernel matches
stock XLA scan inference within noise (~6 ms/call both, bit-identical
outputs) — XLA already keeps this recurrence's carry on-chip at these sizes.
The helper seam's value is the cuDNN-parity architecture: an opt-in kernel
slot per layer family, validated by same-math equivalence tests, ready for
shapes/fusions where the compiler does leave perf on the table. (The win
that did generalize — hoisting the input projection out of the scan — lives
in the default path in ``layers/recurrent.py`` and is helper-independent:
1.62x on LSTM training.)
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.nn.helpers import AttentionHelper, LSTMHelper


def _lstm_kernel(hidden: int, t_total: int,
                 xw_ref, rw_ref, h0_ref, c0_ref,
                 ys_ref, hn_ref, cn_ref, h_scr, c_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    z = xw_ref[0] + jnp.dot(h_scr[:], rw_ref[:],
                            preferred_element_type=jnp.float32).astype(xw_ref.dtype)
    H = hidden
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H])
    o = jax.nn.sigmoid(z[:, 2 * H:3 * H])
    g = jnp.tanh(z[:, 3 * H:])
    c = f * c_scr[:] + i * g
    h = o * jnp.tanh(c)
    h_scr[:] = h
    c_scr[:] = c
    ys_ref[0] = h

    @pl.when(t == t_total - 1)
    def _final():
        hn_ref[:] = h
        cn_ref[:] = c


def _lstm_pallas_fwd(xw, rw, h0, c0, *, interpret: bool):
    """xw [T,N,4H] (input projection + bias), rw [H,4H] → (ys [T,N,H], hN, cN)."""
    T, N, H4 = xw.shape
    H = H4 // 4
    grid = (T,)
    return pl.pallas_call(
        functools.partial(_lstm_kernel, H, T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, N, H4), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
            pl.BlockSpec((N, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, H), xw.dtype),
            jax.ShapeDtypeStruct((N, H), xw.dtype),
            jax.ShapeDtypeStruct((N, H), xw.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, H), xw.dtype),
            pltpu.VMEM((N, H), xw.dtype),
        ],
        interpret=interpret,
    )(xw, rw, h0, c0)


def _lstm_ref_scan(xw, rw, h0, c0):
    """Reference recurrence (identical math to LSTMLayer._cell_pre with
    sigmoid gates / tanh cell): supplies the VJP for the pallas forward."""
    H = rw.shape[0]

    def step(carry, xw_t):
        h_prev, c_prev = carry
        z = xw_t + h_prev @ rw
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H:2 * H])
        o = jax.nn.sigmoid(z[:, 2 * H:3 * H])
        g = jnp.tanh(z[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (hn, cn), ys = jax.lax.scan(step, (h0, c0), xw)
    return ys, hn, cn


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_fused(xw, rw, h0, c0, interpret: bool = False):
    return _lstm_pallas_fwd(xw, rw, h0, c0, interpret=interpret)


def _fused_fwd(xw, rw, h0, c0, interpret):
    out = _lstm_pallas_fwd(xw, rw, h0, c0, interpret=interpret)
    return out, (xw, rw, h0, c0)


def _fused_bwd(interpret, res, cts):
    xw, rw, h0, c0 = res
    _, vjp = jax.vjp(_lstm_ref_scan, xw, rw, h0, c0)
    return vjp(tuple(cts))


lstm_fused.defvjp(_fused_fwd, _fused_bwd)


class PallasLSTMHelper(LSTMHelper):
    """Fused-LSTM helper: standard LSTM (sigmoid gates, tanh cell, no
    peepholes, no mask). ``interpret=True`` runs the kernel in the Pallas
    interpreter (CPU testing)."""

    def __init__(self, interpret: bool = None):
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = interpret

    def supports(self, layer, mask) -> bool:
        return (mask is None
                and not getattr(layer, "peephole", False)
                and layer.gate_activation == "sigmoid"
                and layer.activation in ("tanh",))

    def forward_seq(self, layer, params, x, carry):
        n, t, _ = x.shape
        if carry is None:
            carry = layer.init_carry(n, x.dtype)
        h0, c0 = carry
        xw = jnp.swapaxes(x @ params["W"] + params["b"], 0, 1)  # [T,N,4H]
        rw = params["RW"][:, :4 * layer.n_out]
        ys, hn, cn = lstm_fused(xw, rw, h0, c0, self.interpret)
        return jnp.swapaxes(ys, 0, 1), (hn, cn)


class PallasFlashAttentionHelper(AttentionHelper):
    """Blockwise (flash) attention via the Pallas TPU kernel bundled with
    jax (`jax.experimental.pallas.ops.tpu.flash_attention`) — O(T) memory
    instead of materializing the [N,H,T,T] score matrix, with the module's
    own custom VJP for the backward.

    With the tuned 512-wide block sizes below (measured v5e, 8 heads, dh=64,
    forward): flash beats the einsum path 1.9x at T=8192 (15.9 vs 29.9 ms),
    1.1x at T=4096, and ties at T=1024-2048 — while keeping memory linear in
    T instead of the einsum path's O(T^2) score matrix. Default block sizes
    were 2.5x worse than tuned at T=8192; re-measure per TPU generation.

    Conservative support gate: TPU backend, no mask, no attention dropout,
    sequence length a multiple of 128, head dim in {64, 128, 256} (the tile
    shapes the kernel is built for); everything else falls back to the
    built-in einsum attention.
    """

    def __init__(self, causal: bool = False):
        self.causal = causal

    def supports(self, layer, q_shape, mask, dropout_active,
                 causal=False) -> bool:
        if jax.default_backend() != "tpu":
            return False
        if causal != self.causal:
            # semantics must match the request exactly: a causal kernel must
            # not serve a bidirectional layer and vice versa
            return False
        if mask is not None or dropout_active:
            return False
        t, dh = q_shape[-2], q_shape[-1]
        return t % 128 == 0 and dh in (64, 128, 256)

    @staticmethod
    def _block_sizes(t: int):
        from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

        b = next(c for c in (512, 256, 128) if t % c == 0)
        return BlockSizes(
            block_q=b, block_k_major=b, block_k=b, block_b=1,
            block_q_major_dkv=b, block_k_major_dkv=b, block_k_dkv=b,
            block_q_dkv=b, block_k_major_dq=b, block_k_dq=b, block_q_dq=b)

    def attend(self, q, k, v):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention)

        scale = float(1.0 / (q.shape[-1] ** 0.5))
        return flash_attention(q, k, v, causal=self.causal, sm_scale=scale,
                               block_sizes=self._block_sizes(q.shape[-2]))
