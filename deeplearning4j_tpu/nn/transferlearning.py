"""Transfer learning: fine-tune, freeze, and surgically edit trained nets.

Reference: ``nn/transferlearning/TransferLearning.java:32`` (``Builder:34`` for
MultiLayerNetwork, ``GraphBuilder:447`` for ComputationGraph),
``FineTuneConfiguration.java``, ``TransferLearningHelper.java``.

TPU-native mechanics: a "frozen" layer is the config-level
:class:`FrozenLayer` wrapper whose forward applies ``lax.stop_gradient`` to
its params — XLA then prunes the dead backward graph at compile time, so
frozen layers cost exactly a forward pass (the reference instead skips the
updater). Surgery builds a fresh config and copies retained param arrays
(they are immutable jax arrays — no cloning needed).
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Union

from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
    GraphBuilder,
    VertexDef,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.network import GlobalConf, MultiLayerConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers.base import Layer
from deeplearning4j_tpu.nn.layers.wrappers import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Updater, resolve_updater
from deeplearning4j_tpu.nn.weights import Distribution


_UNSET = object()


def _copy_arrays(d: dict) -> dict:
    """Deep-copy a param/state dict of jax arrays. The fit step donates its
    param buffers to XLA, so two models must never share the same buffers."""
    import jax.numpy as jnp
    return {k: jnp.array(v) for k, v in d.items()}


@dataclasses.dataclass
class FineTuneConfiguration:
    """Global hyperparameter overrides applied to the transferred net
    (``FineTuneConfiguration.java``). Only explicitly set fields override."""

    updater: Optional[Union[str, Updater]] = None
    bias_updater: Optional[Union[str, Updater]] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    distribution: Optional[Distribution] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None
    seed: Optional[int] = None

    def apply_to(self, g: GlobalConf) -> GlobalConf:
        g = copy.deepcopy(g)
        for f in ("activation", "weight_init", "distribution", "bias_init",
                  "l1", "l2", "l1_bias", "l2_bias", "dropout",
                  "gradient_normalization", "gradient_normalization_threshold",
                  "seed"):
            v = getattr(self, f)
            if v is not None:
                setattr(g, f, v)
        if self.updater is not None:
            g.updater = resolve_updater(self.updater)
        if self.bias_updater is not None:
            g.bias_updater = resolve_updater(self.bias_updater)
        return g

    def apply_to_layer(self, layer: Layer) -> None:
        """Clear per-layer values that a fine-tune override should replace, so
        ``apply_global_defaults`` re-inherits them from the new global conf
        (per-layer overrides beat globals in DL4J; fine-tuning resets them on
        every non-frozen layer, ``FineTuneConfiguration.applyToLayer``)."""
        if isinstance(layer, FrozenLayer):
            return
        for f in ("updater", "bias_updater", "activation", "weight_init",
                  "distribution", "bias_init", "dropout", "l1", "l2",
                  "l1_bias", "l2_bias", "gradient_normalization"):
            if getattr(self, f) is not None:
                setattr(layer, f, None)
        if self.gradient_normalization_threshold is not None:
            layer.gradient_normalization_threshold = self.gradient_normalization_threshold


class TransferLearning:
    """Namespace matching the reference API: ``TransferLearning.Builder`` for
    sequential nets, ``TransferLearning.GraphBuilder`` for DAGs."""

    class Builder:
        """Surgery on a trained MultiLayerNetwork (``TransferLearning.Builder``)."""

        def __init__(self, net: MultiLayerNetwork):
            if net.params is None:
                raise ValueError("network must be initialized (call .init())")
            self._net = net
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_until: Optional[int] = None
            # surgery recorded as (op, args) applied in order at build()
            self._removed_from_output = 0
            self._appended: List[Layer] = []
            self._nout_replaced: Dict[int, tuple] = {}
            self._input_type: Optional[InputType] = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration) -> "TransferLearning.Builder":
            self._ftc = ftc
            return self

        def set_feature_extractor(self, layer_index: int) -> "TransferLearning.Builder":
            """Freeze layers [0, layer_index] (inclusive)."""
            self._freeze_until = layer_index
            return self

        def remove_output_layer(self) -> "TransferLearning.Builder":
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int) -> "TransferLearning.Builder":
            self._removed_from_output += n
            return self

        def add_layer(self, layer: Layer) -> "TransferLearning.Builder":
            self._appended.append(layer)
            return self

        def n_out_replace(self, layer_index: int, n_out: int,
                          weight_init: Optional[str] = None,
                          distribution: Optional[Distribution] = None) -> "TransferLearning.Builder":
            """Change layer ``layer_index``'s n_out; that layer and its
            consumer are re-initialized (``TransferLearning.nOutReplace``)."""
            self._nout_replaced[layer_index] = (n_out, weight_init, distribution)
            return self

        def set_input_type(self, it: InputType) -> "TransferLearning.Builder":
            self._input_type = it
            return self

        def build(self) -> MultiLayerNetwork:
            old_conf = self._net.conf
            n_old = len(old_conf.layers)
            keep = n_old - self._removed_from_output
            if keep < 0:
                raise ValueError("removed more layers than the network has")

            new_layers: List[Layer] = [copy.deepcopy(old_conf.layers[i])
                                       for i in range(keep)]
            reinit: Set[int] = set()

            def inner_of(l: Layer) -> Layer:
                return l.layer if isinstance(l, FrozenLayer) else l

            for i, (n_out, w, dist) in self._nout_replaced.items():
                inner = inner_of(new_layers[i])
                inner.n_out = n_out
                if w is not None:
                    inner.weight_init = w
                if dist is not None:
                    inner.distribution = dist
                reinit.add(i)
                if i + 1 < keep:
                    nxt = inner_of(new_layers[i + 1])
                    if hasattr(nxt, "n_in"):
                        nxt.n_in = 0  # re-infer from the new upstream width
                    reinit.add(i + 1)
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, keep)):
                    if not isinstance(new_layers[i], FrozenLayer):
                        new_layers[i] = FrozenLayer(layer=new_layers[i])
            for j, l in enumerate(self._appended):
                reinit.add(keep + j)
                new_layers.append(copy.deepcopy(l))

            g = old_conf.global_conf
            if self._ftc is not None:
                g = self._ftc.apply_to(g)
                for i, l in enumerate(new_layers):
                    if self._freeze_until is None or i > self._freeze_until:
                        self._ftc.apply_to_layer(l)

            new_conf = MultiLayerConfiguration(
                global_conf=g,
                layers=new_layers,
                input_type=self._input_type or old_conf.input_type,
                backprop_type=old_conf.backprop_type,
                tbptt_fwd_length=old_conf.tbptt_fwd_length,
                tbptt_bwd_length=old_conf.tbptt_bwd_length,
            )
            new_conf.finalize()
            new_net = MultiLayerNetwork(new_conf).init(seed=g.seed)
            # copy retained params (old arrays are immutable; share directly)
            for i in range(keep):
                if i not in reinit:
                    new_net.params[i] = _copy_arrays(self._net.params[i])
                    new_net.states[i] = _copy_arrays(self._net.states[i])
            return new_net

    class GraphBuilder:
        """Surgery on a trained ComputationGraph (``TransferLearning.GraphBuilder:447``)."""

        def __init__(self, graph: ComputationGraph):
            if graph.params is None:
                raise ValueError("graph must be initialized (call .init())")
            self._graph = graph
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_boundary: List[str] = []
            self._removed: Set[str] = set()
            self._added: List[VertexDef] = []
            self._nout_replaced: Dict[str, tuple] = {}
            self._outputs: Optional[List[str]] = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration) -> "TransferLearning.GraphBuilder":
            self._ftc = ftc
            return self

        def set_feature_extractor(self, *vertex_names: str) -> "TransferLearning.GraphBuilder":
            """Freeze the named vertices and every ancestor of them."""
            self._freeze_boundary = list(vertex_names)
            return self

        def remove_vertex(self, name: str, remove_connections: bool = True) -> "TransferLearning.GraphBuilder":
            self._removed.add(name)
            if remove_connections:
                # downstream-only removal: also drop vertices that depend on it
                conf = self._graph.conf
                changed = True
                while changed:
                    changed = False
                    for vn, vd in conf.vertices.items():
                        if vn in self._removed:
                            continue
                        if any(s in self._removed for s in vd.inputs):
                            self._removed.add(vn)
                            changed = True
            return self

        def add_layer(self, name: str, layer: Layer, *inputs: str) -> "TransferLearning.GraphBuilder":
            layer.name = layer.name or name
            self._added.append(VertexDef(name, layer, list(inputs)))
            return self

        def add_vertex(self, name: str, vertex, *inputs: str) -> "TransferLearning.GraphBuilder":
            self._added.append(VertexDef(name, vertex, list(inputs)))
            return self

        def set_outputs(self, *names: str) -> "TransferLearning.GraphBuilder":
            self._outputs = list(names)
            return self

        def n_out_replace(self, name: str, n_out: int,
                          weight_init: Optional[str] = None,
                          distribution: Optional[Distribution] = None) -> "TransferLearning.GraphBuilder":
            self._nout_replaced[name] = (n_out, weight_init, distribution)
            return self

        def _ancestors(self, names: Sequence[str]) -> Set[str]:
            conf = self._graph.conf
            out: Set[str] = set()
            stack = [n for n in names if n in conf.vertices]
            while stack:
                n = stack.pop()
                if n in out:
                    continue
                out.add(n)
                for src in conf.vertices[n].inputs:
                    if src in conf.vertices:
                        stack.append(src)
            return out

        def build(self) -> ComputationGraph:
            old = self._graph.conf
            frozen = self._ancestors(self._freeze_boundary)
            reinit: Set[str] = set()

            # consumers of an n_out-replaced vertex must re-infer their n_in
            consumers: Dict[str, List[str]] = {}
            for vn, vd in old.vertices.items():
                for s in vd.inputs:
                    consumers.setdefault(s, []).append(vn)

            g = old.global_conf
            if self._ftc is not None:
                g = self._ftc.apply_to(g)

            vertices: Dict[str, VertexDef] = {}
            for vn in old.topo_order:
                if vn in self._removed:
                    continue
                vd = old.vertices[vn]
                obj = copy.deepcopy(vd.obj)
                if vn in self._nout_replaced and vd.is_layer:
                    n_out, w, dist = self._nout_replaced[vn]
                    inner = obj.layer if isinstance(obj, FrozenLayer) else obj
                    inner.n_out = n_out
                    if w is not None:
                        inner.weight_init = w
                    if dist is not None:
                        inner.distribution = dist
                    reinit.add(vn)
                    for cn in consumers.get(vn, []):
                        cvd = old.vertices[cn]
                        if cvd.is_layer:
                            reinit.add(cn)
                if vn in frozen and vd.is_layer and not isinstance(obj, FrozenLayer):
                    obj = FrozenLayer(layer=obj)
                if vd.is_layer and self._ftc is not None and vn not in frozen:
                    self._ftc.apply_to_layer(obj)
                vertices[vn] = VertexDef(vn, obj, list(vd.inputs))
            for vd in self._added:
                reinit.add(vd.name)
                vertices[vd.name] = vd

            # consumers of reinit'd layers need n_in re-inferred
            for vn in list(reinit):
                for cn in consumers.get(vn, []):
                    if cn in vertices and vertices[cn].is_layer:
                        obj = vertices[cn].obj
                        inner = obj.layer if isinstance(obj, FrozenLayer) else obj
                        if hasattr(inner, "n_in"):
                            inner.n_in = 0
                            reinit.add(cn)

            outputs = self._outputs or [o for o in old.outputs if o in vertices]
            new_conf = ComputationGraphConfiguration(
                global_conf=g,
                inputs=list(old.inputs),
                outputs=outputs,
                vertices=vertices,
                input_types=list(old.input_types),
                backprop_type=old.backprop_type,
                tbptt_fwd_length=old.tbptt_fwd_length,
                tbptt_bwd_length=old.tbptt_bwd_length,
            )
            new_conf.finalize()
            new_graph = ComputationGraph(new_conf).init(seed=g.seed)
            for vn, p in self._graph.params.items():
                if vn in vertices and vn not in reinit:
                    new_graph.params[vn] = _copy_arrays(p)
                    new_graph.states[vn] = _copy_arrays(self._graph.states[vn])
            return new_graph


class TransferLearningHelper:
    """Featurization helper (``TransferLearningHelper.java``): runs the frozen
    trunk once per example and trains only the unfrozen head on the cached
    features — the reference's featurize/fitFeaturized workflow."""

    def __init__(self, net: MultiLayerNetwork, frozen_till: int):
        self._net = net
        self._split = frozen_till + 1
        if self._split >= len(net.layers):
            raise ValueError("frozen_till must leave at least one trainable layer")
        head_layers = [copy.deepcopy(l) for l in net.conf.layers[self._split:]]
        head_conf = MultiLayerConfiguration(
            global_conf=net.conf.global_conf,
            layers=head_layers,
            input_type=net.conf.layer_input_types[self._split],
            backprop_type=net.conf.backprop_type,
            tbptt_fwd_length=net.conf.tbptt_fwd_length,
            tbptt_bwd_length=net.conf.tbptt_bwd_length,
        )
        head_conf.finalize()
        self._head = MultiLayerNetwork(head_conf).init(seed=net.conf.global_conf.seed)
        for j in range(len(head_layers)):
            self._head.params[j] = _copy_arrays(net.params[self._split + j])
            self._head.states[j] = _copy_arrays(net.states[self._split + j])

    @property
    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self._head

    def featurize(self, ds):
        """Run the frozen trunk forward; returns a DataSet of features."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        import numpy as np
        acts = self._net.feed_forward(ds.features)[self._split]
        # the head conf's input_type is post-preprocessor, so apply the
        # original net's preprocessor for the first head layer here
        pre = self._net.conf.preprocessors.get(self._split)
        if pre is not None:
            acts = pre(acts)
        return DataSet(np.asarray(acts), np.asarray(ds.labels))

    def fit_featurized(self, ds, epochs: int = 1) -> None:
        self._head.fit(ds.features, ds.labels, epochs=epochs)

    def output_from_featurized(self, features):
        return self._head.output(features)
