"""Kafka client for array streams, gated on kafka-python availability.

Parity with `dl4j-streaming/.../streaming/kafka/NDArrayKafkaClient.java` (and
its NDArrayPublisher/NDArrayConsumer): publish/consume arrays on a Kafka
topic. The environment has no Kafka broker or client library baked in, so
construction degrades to the in-process :class:`EmbeddedBroker` unless
``kafka-python`` is importable — the same frames flow either way.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.streaming.broker import EmbeddedBroker
from deeplearning4j_tpu.streaming.codec import deserialize_array, serialize_array


def _kafka_available() -> bool:
    try:
        import kafka  # noqa: F401
        return True
    except ImportError:
        return False


class NDArrayKafkaClient:
    """Publish/consume numpy arrays on a topic."""

    def __init__(self, bootstrap_servers: Optional[str] = None,
                 topic: str = "ndarrays",
                 embedded: Optional[EmbeddedBroker] = None):
        self.topic = topic
        self._producer = self._consumer = None
        if bootstrap_servers is not None and _kafka_available():
            from kafka import KafkaConsumer, KafkaProducer
            self._producer = KafkaProducer(bootstrap_servers=bootstrap_servers)
            self._consumer = KafkaConsumer(topic,
                                           bootstrap_servers=bootstrap_servers)
            self._broker = None
        elif bootstrap_servers is not None:
            raise ImportError(
                "kafka-python is not installed; pass embedded=EmbeddedBroker() "
                "for the in-process transport or install kafka-python")
        else:
            self._broker = embedded or EmbeddedBroker()

    def publish(self, array) -> None:
        frame = serialize_array(array)
        if self._producer is not None:
            self._producer.send(self.topic, frame)
            self._producer.flush()
        else:
            self._broker.publish(self.topic, frame)

    def poll(self, timeout: float = 5.0):
        if self._consumer is not None:
            records = self._consumer.poll(timeout_ms=int(timeout * 1000))
            for batch in records.values():
                for rec in batch:
                    return deserialize_array(rec.value)
            return None
        frame = self._broker.poll(self.topic, timeout=timeout)
        return None if frame is None else deserialize_array(frame)
