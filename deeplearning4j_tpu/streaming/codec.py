"""Wire format for arrays and DataSets.

Role of the reference's NDArray↔record conversion inside the Kafka/Camel
routes (`dl4j-streaming/.../streaming/conversion/`): a self-describing binary
frame — 4-byte big-endian JSON-header length, JSON header (dtype, shape,
fields), raw C-order array bytes concatenated.
"""

from __future__ import annotations

import json
import struct
from typing import Optional

import numpy as np


def serialize_array(arr) -> bytes:
    a = np.ascontiguousarray(np.asarray(arr))
    header = json.dumps({"dtype": str(a.dtype), "shape": list(a.shape)}).encode()
    return struct.pack(">I", len(header)) + header + a.tobytes()


def deserialize_array(data: bytes) -> np.ndarray:
    hlen = struct.unpack(">I", data[:4])[0]
    header = json.loads(data[4:4 + hlen].decode())
    a = np.frombuffer(data[4 + hlen:], dtype=np.dtype(header["dtype"]))
    return a.reshape(header["shape"]).copy()


def serialize_dataset(ds) -> bytes:
    parts = {"features": np.asarray(ds.features), "labels": np.asarray(ds.labels)}
    if ds.features_mask is not None:
        parts["features_mask"] = np.asarray(ds.features_mask)
    if ds.labels_mask is not None:
        parts["labels_mask"] = np.asarray(ds.labels_mask)
    blobs = {k: serialize_array(v) for k, v in parts.items()}
    header = json.dumps({k: len(v) for k, v in blobs.items()}).encode()
    return (struct.pack(">I", len(header)) + header
            + b"".join(blobs[k] for k in sorted(blobs)))


def deserialize_dataset(data: bytes):
    from deeplearning4j_tpu.datasets.dataset import DataSet

    hlen = struct.unpack(">I", data[:4])[0]
    sizes = json.loads(data[4:4 + hlen].decode())
    arrays = {}
    off = 4 + hlen
    for k in sorted(sizes):
        arrays[k] = deserialize_array(data[off:off + sizes[k]])
        off += sizes[k]
    return DataSet(arrays["features"], arrays["labels"],
                   arrays.get("features_mask"), arrays.get("labels_mask"))
