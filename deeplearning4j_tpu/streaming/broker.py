"""In-process topic broker + TCP transport + streaming DataSet iterator.

``EmbeddedBroker`` plays the role of the reference's embedded Kafka/ZooKeeper
test cluster (`streaming/embedded/EmbeddedKafkaCluster.java`): real topic
semantics (named topics, multiple independent consumer groups, blocking
polls) without any external service. ``SocketPublisher``/``SocketConsumer``
carry the same frames across processes over TCP — the role Kafka plays in
production for the reference.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Dict, List, Optional

from deeplearning4j_tpu.streaming.codec import (
    deserialize_dataset,
    serialize_dataset,
)


class EmbeddedBroker:
    """Named topics; each consumer group gets every message once."""

    def __init__(self):
        self._topics: Dict[str, Dict[str, "queue.Queue[bytes]"]] = {}
        self._lock = threading.Lock()

    def _groups(self, topic: str) -> Dict[str, "queue.Queue[bytes]"]:
        with self._lock:
            return self._topics.setdefault(topic, {})

    def subscribe(self, topic: str, group: str = "default") -> "queue.Queue[bytes]":
        groups = self._groups(topic)
        with self._lock:
            return groups.setdefault(group, queue.Queue())

    def publish(self, topic: str, payload: bytes) -> None:
        groups = self._groups(topic)
        with self._lock:
            if not groups:
                groups.setdefault("default", queue.Queue())
            targets = list(groups.values())
        for q in targets:
            q.put(payload)

    def poll(self, topic: str, group: str = "default",
             timeout: Optional[float] = None) -> Optional[bytes]:
        q = self.subscribe(topic, group)
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            return None


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    head = b""
    while len(head) < 4:
        chunk = sock.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    n = struct.unpack(">I", head)[0]
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return buf


class SocketConsumer:
    """Listens on a TCP port, feeding received frames into a local queue
    (the consumer end of the production transport)."""

    def __init__(self, port: int = 0):
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(4)
        self.port = self._server.getsockname()[1]
        self.queue: "queue.Queue[bytes]" = queue.Queue()
        self._running = True
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket):
        with conn:
            while True:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                self.queue.put(frame)

    def poll(self, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self):
        self._running = False
        self._server.close()


class SocketPublisher:
    """Publishes frames to a SocketConsumer."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)

    def publish(self, payload: bytes) -> None:
        _send_frame(self._sock, payload)

    def close(self):
        self._sock.close()


class StreamingDataSetIterator:
    """Consumes serialized DataSets from a topic until ``num_batches`` (or a
    poll timeout) — plugs a stream into ``net.fit`` exactly like the
    reference's Camel route → iterator glue."""

    def __init__(self, source, topic: Optional[str] = None,
                 group: str = "default", num_batches: Optional[int] = None,
                 poll_timeout: float = 5.0):
        self.source = source
        self.topic = topic
        self.group = group
        self.num_batches = num_batches
        self.poll_timeout = poll_timeout

    def reset(self) -> None:
        pass  # a stream cannot be rewound

    def _poll(self) -> Optional[bytes]:
        if self.topic is not None:
            return self.source.poll(self.topic, self.group,
                                    timeout=self.poll_timeout)
        return self.source.poll(timeout=self.poll_timeout)

    def __iter__(self):
        n = 0
        while self.num_batches is None or n < self.num_batches:
            frame = self._poll()
            if frame is None:
                return
            yield deserialize_dataset(frame)
            n += 1
