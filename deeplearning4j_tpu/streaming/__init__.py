"""Streaming ingest: pub/sub of arrays and DataSets into training loops.

Re-design of ``deeplearning4j-scaleout/dl4j-streaming`` (Kafka pub/sub of
NDArrays via `streaming/kafka/NDArrayKafkaClient.java`, Camel route glue, and
the embedded Kafka/ZooKeeper test cluster
`streaming/embedded/EmbeddedKafkaCluster.java`): an in-process broker with
identical topic semantics for tests and single-host pipelines, a TCP
publisher/consumer pair for cross-process streams, a kafka-python client
used automatically when the library is installed, and a
``StreamingDataSetIterator`` that feeds a fit loop from a topic.
"""

from deeplearning4j_tpu.streaming.codec import (  # noqa: F401
    deserialize_array,
    deserialize_dataset,
    serialize_array,
    serialize_dataset,
)
from deeplearning4j_tpu.streaming.broker import (  # noqa: F401
    EmbeddedBroker,
    SocketConsumer,
    SocketPublisher,
    StreamingDataSetIterator,
)
from deeplearning4j_tpu.streaming.kafka import NDArrayKafkaClient  # noqa: F401
from deeplearning4j_tpu.streaming.route import Route, RouteError  # noqa: F401
