"""Route builder: source → transforms → sink pipelines.

The role of the reference's Camel routes (`dl4j-streaming/.../routes/`,
e.g. CSV → NDArray → Kafka): a small fluent pipeline that pulls from a
source, applies transforms, and pushes into a broker topic / socket / list,
optionally on a background thread.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, List, Optional


class Route:
    """``Route().from_source(it).transform(f).to_topic(broker, "t").start()``"""

    def __init__(self):
        self._source: Optional[Iterable] = None
        self._transforms: List[Callable[[Any], Any]] = []
        self._sink: Optional[Callable[[Any], None]] = None
        self._thread: Optional[threading.Thread] = None

    def from_source(self, iterable: Iterable) -> "Route":
        self._source = iterable
        return self

    def transform(self, fn: Callable[[Any], Any]) -> "Route":
        self._transforms.append(("map", fn))
        return self

    def filter(self, predicate: Callable[[Any], bool]) -> "Route":
        self._transforms.append(("filter", predicate))
        return self

    def to_topic(self, broker, topic: str,
                 serializer: Optional[Callable[[Any], bytes]] = None) -> "Route":
        def sink(item):
            broker.publish(topic, serializer(item) if serializer else item)
        self._sink = sink
        return self

    def to_callable(self, fn: Callable[[Any], None]) -> "Route":
        self._sink = fn
        return self

    def to_list(self, out: List[Any]) -> "Route":
        self._sink = out.append
        return self

    def run(self) -> int:
        """Drain the source synchronously; returns items delivered."""
        if self._source is None or self._sink is None:
            raise ValueError("route needs from_source(...) and a to_*(...) sink")
        n = 0
        for item in self._source:
            dropped = False
            for kind, fn in self._transforms:
                if kind == "map":
                    item = fn(item)
                elif not fn(item):  # filter
                    dropped = True
                    break
            if dropped:
                continue
            self._sink(item)
            n += 1
        return n

    def start(self) -> "Route":
        """Run on a background thread (Camel's async route start)."""
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
