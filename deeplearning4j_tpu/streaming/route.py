"""Route builder: source → transforms → sink pipelines.

The role of the reference's Camel routes (`dl4j-streaming/.../routes/`,
e.g. CSV → NDArray → Kafka): a small fluent pipeline that pulls from a
source, applies transforms, and pushes into a broker topic / socket / list,
optionally on a background thread.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, List, Optional, Tuple


class RouteError(RuntimeError):
    """A transform/sink raised under the ``stop`` policy; chains the cause
    and carries the offending ``item``."""

    def __init__(self, item: Any, cause: Exception):
        super().__init__(f"route failed on item {item!r}: {cause!r}")
        self.item = item


class Route:
    """``Route().from_source(it).transform(f).to_topic(broker, "t").start()``

    Error policy (``on_error``): what a throwing transform/sink does —
    - ``'stop'`` (default): processing stops and the error SURFACES — a
      synchronous ``run()`` raises ``RouteError``; a background ``start()``
      records it in ``route.error`` (a route thread never dies silently);
    - ``'skip'``: the item is dropped, the (item, exception) pair appended
      to ``route.errors``, and the route continues (Camel's
      dead-letter-channel role);
    - a callable ``fn(item, exc)``: invoked per failure, route continues;
      if the handler itself raises, that escalates as ``stop`` would.
    """

    def __init__(self):
        self._source: Optional[Iterable] = None
        self._transforms: List[Callable[[Any], Any]] = []
        self._sink: Optional[Callable[[Any], None]] = None
        self._thread: Optional[threading.Thread] = None
        self._on_error: Any = "stop"
        self.error: Optional[Exception] = None
        self.errors: List[Tuple[Any, Exception]] = []

    def from_source(self, iterable: Iterable) -> "Route":
        self._source = iterable
        return self

    def transform(self, fn: Callable[[Any], Any]) -> "Route":
        self._transforms.append(("map", fn))
        return self

    def filter(self, predicate: Callable[[Any], bool]) -> "Route":
        self._transforms.append(("filter", predicate))
        return self

    def to_topic(self, broker, topic: str,
                 serializer: Optional[Callable[[Any], bytes]] = None) -> "Route":
        def sink(item):
            broker.publish(topic, serializer(item) if serializer else item)
        self._sink = sink
        return self

    def to_callable(self, fn: Callable[[Any], None]) -> "Route":
        self._sink = fn
        return self

    def to_list(self, out: List[Any]) -> "Route":
        self._sink = out.append
        return self

    def on_error(self, policy) -> "Route":
        """``'stop'`` | ``'skip'`` | ``fn(item, exc)`` — see class docs."""
        if policy not in ("stop", "skip") and not callable(policy):
            raise ValueError(
                f"on_error must be 'stop', 'skip' or a callable, "
                f"got {policy!r}")
        self._on_error = policy
        return self

    def run(self) -> int:
        """Drain the source synchronously; returns items delivered."""
        if self._source is None or self._sink is None:
            raise ValueError("route needs from_source(...) and a to_*(...) sink")
        n = 0
        for item in self._source:
            original = item
            try:
                dropped = False
                for kind, fn in self._transforms:
                    if kind == "map":
                        item = fn(item)
                    elif not fn(item):  # filter
                        dropped = True
                        break
                if dropped:
                    continue
                self._sink(item)
            except Exception as e:  # noqa: BLE001 - policy decides
                if self._on_error == "skip":
                    self.errors.append((original, e))
                    continue
                if callable(self._on_error):
                    try:
                        self._on_error(original, e)
                    except Exception as handler_exc:  # noqa: BLE001
                        # handler failure escalates like 'stop' — same
                        # RouteError contract, carrying the offending item
                        raise RouteError(original, handler_exc) from handler_exc
                    self.errors.append((original, e))
                    continue
                raise RouteError(original, e) from e
            n += 1
        return n

    def start(self) -> "Route":
        """Run on a background thread (Camel's async route start). A
        failure under the ``stop`` policy lands in ``self.error`` instead
        of vanishing with the thread."""
        def guarded():
            try:
                self.run()
            except Exception as e:  # noqa: BLE001 - surfaced via .error
                self.error = e

        self._thread = threading.Thread(target=guarded, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
