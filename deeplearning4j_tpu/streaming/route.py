"""Route builder: source → transforms → sink pipelines.

The role of the reference's Camel routes (`dl4j-streaming/.../routes/`,
e.g. CSV → NDArray → Kafka): a small fluent pipeline that pulls from a
source, applies transforms, and pushes into a broker topic / socket / list,
optionally on a background thread.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.observe import trace as _trace


class RouteError(RuntimeError):
    """A transform/sink raised under the ``stop`` policy; chains the cause
    and carries the offending ``item``."""

    def __init__(self, item: Any, cause: Exception):
        super().__init__(f"route failed on item {item!r}: {cause!r}")
        self.item = item


_DROPPED = object()  # sentinel: a filter rejected the item


class Route:
    """``Route().from_source(it).transform(f).to_topic(broker, "t").start()``

    Error policy (``on_error``): what a throwing transform/sink does —
    - ``'stop'`` (default): processing stops and the error SURFACES — a
      synchronous ``run()`` raises ``RouteError``; a background ``start()``
      records it in ``route.error`` (a route thread never dies silently);
    - ``'skip'``: the item is dropped, the (item, exception) pair appended
      to ``route.errors``, and the route continues (Camel's
      dead-letter-channel role);
    - a callable ``fn(item, exc)``: invoked per failure, route continues;
      if the handler itself raises, that escalates as ``stop`` would.
    """

    def __init__(self):
        self._source: Optional[Iterable] = None
        self._transforms: List[Callable[[Any], Any]] = []
        self._sink: Optional[Callable[[Any], None]] = None
        self._thread: Optional[threading.Thread] = None
        self._on_error: Any = "stop"
        self.error: Optional[Exception] = None
        self.errors: List[Tuple[Any, Exception]] = []
        # items delivered by a completed background run (None while the
        # route is still running / never started) — consumers like the
        # pipeline trainer use it to tell "drained" from "stuck"
        self.result: Optional[int] = None

    def from_source(self, iterable: Iterable) -> "Route":
        self._source = iterable
        return self

    def transform(self, fn: Callable[[Any], Any]) -> "Route":
        self._transforms.append(("map", fn))
        return self

    def filter(self, predicate: Callable[[Any], bool]) -> "Route":
        self._transforms.append(("filter", predicate))
        return self

    def to_topic(self, broker, topic: str,
                 serializer: Optional[Callable[[Any], bytes]] = None) -> "Route":
        def sink(item):
            broker.publish(topic, serializer(item) if serializer else item)
        self._sink = sink
        return self

    def to_callable(self, fn: Callable[[Any], None]) -> "Route":
        self._sink = fn
        return self

    def to_list(self, out: List[Any]) -> "Route":
        self._sink = out.append
        return self

    def on_error(self, policy) -> "Route":
        """``'stop'`` | ``'skip'`` | ``fn(item, exc)`` — see class docs."""
        if policy not in ("stop", "skip") and not callable(policy):
            raise ValueError(
                f"on_error must be 'stop', 'skip' or a callable, "
                f"got {policy!r}")
        self._on_error = policy
        return self

    def run(self) -> int:
        """Drain the source synchronously; returns items delivered.

        When a tracer is active (``observe.enable_tracing``), the drain
        runs inside a ``route.run`` span with one ``route.item`` span per
        item and a child span per transform/sink stage — a failing or slow
        stage is visible in the same timeline as the training steps and
        serving requests it feeds."""
        if self._source is None or self._sink is None:
            raise ValueError("route needs from_source(...) and a to_*(...) sink")
        tracer = _trace.get_active_tracer()
        with _trace.span("route.run", category="stream"):
            return self._run_items(tracer)

    def _run_items(self, tracer) -> int:
        n = 0
        for index, item in enumerate(self._source):
            original = item
            try:
                item = self._process_item(tracer, item, index)
                if item is _DROPPED:
                    continue
            except Exception as e:  # noqa: BLE001 - policy decides
                if self._on_error == "skip":
                    self.errors.append((original, e))
                    continue
                if callable(self._on_error):
                    try:
                        self._on_error(original, e)
                    except Exception as handler_exc:  # noqa: BLE001
                        # handler failure escalates like 'stop' — same
                        # RouteError contract, carrying the offending item
                        raise RouteError(original, handler_exc) from handler_exc
                    self.errors.append((original, e))
                    continue
                raise RouteError(original, e) from e
            n += 1
        return n

    def _process_item(self, tracer, item, index):
        """Transforms + sink for one item; returns ``_DROPPED`` when a
        filter rejects it. Stage spans only exist while tracing is on."""
        if tracer is None:
            for kind, fn in self._transforms:
                if kind == "map":
                    item = fn(item)
                elif not fn(item):  # filter
                    return _DROPPED
            self._sink(item)
            return item
        with tracer.span("route.item", category="stream",
                         attrs={"index": index}):
            for kind, fn in self._transforms:
                stage = getattr(fn, "__name__", None) or type(fn).__name__
                with tracer.span(f"{kind}:{stage}", category="stream"):
                    if kind == "map":
                        item = fn(item)
                    elif not fn(item):  # filter
                        return _DROPPED
            with tracer.span("sink", category="stream"):
                self._sink(item)
        return item

    def start(self) -> "Route":
        """Run on a background thread (Camel's async route start). A
        failure under the ``stop`` policy lands in ``self.error`` instead
        of vanishing with the thread; a clean drain records the delivered
        count in ``self.result``."""
        def guarded():
            try:
                self.result = self.run()
            except Exception as e:  # noqa: BLE001 - surfaced via .error
                self.error = e

        self._thread = threading.Thread(target=guarded, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> Optional[int]:
        """Wait for a background route; returns the delivered-item count
        (``None`` when the route stopped on an error — see ``.error``).

        Raises ``TimeoutError`` when the route is still running after
        ``timeout`` seconds: a stuck stream must be distinguishable from
        a drained one, not a silent return."""
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(f"route still running after {timeout}s")
        return self.result
