"""HTTP front-end: the network surface of the model-serving subsystem.

stdlib ``http.server`` (the same Play→stdlib translation as the KNN and UI
servers), one OS thread per connection, composing registry + admission +
metrics into the production request path:

==============================================  ==================================
endpoint                                        behavior
==============================================  ==================================
``POST /v1/models/<name>[:<version>]/predict``  JSON ``{"inputs": [...]}`` or the
                                                ``streaming/codec.py`` binary array
                                                frame (``application/octet-stream``);
                                                response mirrors the request type
``GET /v1/models``                              registry listing (versions, health,
                                                per-version canary traffic weights
                                                and shadow-experiment counters when
                                                a canary is in flight)
``GET /v1/models/<name>``                       one model's description
``GET /healthz``                                process liveness (always 200)
``GET /readyz``                                 readiness — 503 while draining, mid
                                                hot-swap, empty, dispatcher-dead, or
                                                bucket warmup incomplete (body lists
                                                the cold buckets per model)
``GET /livez``                                  condensed ``HealthReport`` status
                                                (``?verbose=1`` → full check list);
                                                503 only when a critical probe
                                                fails (dead dispatcher)
``GET /alerts``                                 the attached ``AlertManager``'s
                                                rule states + firing set
``GET /slo``                                    the attached ``SLOSet``'s
                                                compliance + burn rates + rule
                                                states (``observe/slo.py``)
``GET /metrics``                                Prometheus text exposition
``GET /debug/capture?seconds=N``                on-demand mini bundle: last-N-
                                                seconds spans as a Chrome trace
                                                + metrics snapshot + cost-ledger
                                                slice (``observe.incident
                                                .capture_bundle`` bounds)
==============================================  ==================================

Request cost: every dispatcher-served predict response carries
``X-Device-Ms`` — the request's row-weighted share of its batches'
device time (compile time excluded), billed from the shared
``observe.cost.CostLedger`` that ``/v1/models`` also surfaces.

Status mapping (the contract the tests reconcile against the metrics):
200 served · 400 malformed · 404 unknown model/version · 429 + ``Retry-After``
admission overflow/brownout shed · 500 model error · 503 + ``Retry-After``
draining/dispatcher-dead/quarantined · 504 deadline exceeded (expired
requests are never dispatched to the device).

Per-request deadlines ride the ``X-Deadline-Ms`` header (or ``deadline_ms``
in a JSON body) and propagate into the batching dispatcher.

Serving resilience (round 13): every dispatcher-crash 503 carries
``Retry-After`` (the supervised restart's remaining backoff when one is
pending); a breaker failover or brownout reroute answers 200 with an
``X-Degraded: breaker|brownout`` header and the version that actually
served. Request priorities ride ``X-Priority`` (0 batch, 1 standard,
2 interactive); while the attached :class:`BrownoutController` is engaged,
low-priority requests shed with 429 + ``Retry-After`` and un-pinned
predicts degrade to the registry's fallback chain. The serving chaos
faults (``util/faultinject.py``: ``reject_admission`` / ``drop_response``)
hook the front door here, keyed on a per-model request sequence.

Canary routing: un-pinned predict requests honor the registry's live
traffic split (``ModelRegistry.set_traffic_split`` — the ``pipeline/``
subsystem's canary data plane); the ``version`` field / ``X-Model-Version``
header in the response reports which version actually served, so a client
can tell it was canaried.  Shadow mode duplicates sampled live requests to
the candidate off the response path — the HTTP handler never waits on it.

Distributed tracing: a W3C ``traceparent`` request header joins the
caller's trace — the predict path runs inside an ``http_request`` span
parented to it (handler threads nest the dispatcher's ``queue_wait`` /
``batch_execute`` spans under the same trace via the request context), and
every predict response echoes ``X-Trace-Id`` (plus a ``traceparent`` of the
server's own span while tracing is active) so callers can correlate.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.observe.metrics import (MetricsRegistry,
                                                default_registry)
from deeplearning4j_tpu.observe.metrics import respond as _respond_http
from deeplearning4j_tpu.observe.metrics import respond_json as _respond_json
from deeplearning4j_tpu.parallel.inference import (DispatcherCrashed,
                                                   InferenceDeadlineExceeded)
from deeplearning4j_tpu.serving.admission import (AdmissionController,
                                                  AdmissionRejected, Draining)
from deeplearning4j_tpu.serving.brownout import BrownoutController
from deeplearning4j_tpu.serving.registry import (ModelNotFound,
                                                 ModelRegistry,
                                                 VersionQuarantined)
from deeplearning4j_tpu.streaming.codec import (deserialize_array,
                                                serialize_array)
from deeplearning4j_tpu.util import faultinject as _faultinject

BINARY_CONTENT_TYPE = "application/octet-stream"


class _DroppedResponder:
    """Stand-in handler for a ``drop_response`` chaos fault: the request
    is processed for real (admission, dispatch, metrics) but every write
    is swallowed — then the server severs the connection, exactly like a
    network that ate the answer after the work was done."""

    __slots__ = ("headers",)

    def __init__(self, handler):
        self.headers = handler.headers

    def _json(self, *a, **k) -> None:
        pass

    def _respond(self, *a, **k) -> None:
        pass


class ModelServer:
    """Production inference front-end over a ``ModelRegistry``."""

    def __init__(self, registry: ModelRegistry, *,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics: Optional[MetricsRegistry] = None,
                 max_inflight: int = 64, retry_after_s: float = 0.05,
                 default_deadline_s: Optional[float] = None,
                 alerts=None, brownout=None, slo=None, cost=None):
        self.registry = registry
        self.host = host
        self.port = port
        self.metrics = metrics if metrics is not None else default_registry()
        self.default_deadline_s = default_deadline_s
        self.admission = AdmissionController(
            max_inflight, retry_after_s=retry_after_s, metrics=self.metrics)
        self.alerts = alerts  # an observe.alerts.AlertManager, or None
        self.slo = slo        # an observe.slo.SLOSet, or None
        # the cost ledger is always on (the X-Device-Ms / /v1/models
        # contract): use the one given, else the registry's, else a fresh
        # one — and make sure the registry's dispatchers feed it
        if cost is None:
            cost = getattr(registry, "cost", None)
        if cost is None:
            from deeplearning4j_tpu.observe.cost import CostLedger
            cost = CostLedger(self.metrics)
        self.cost = cost
        if getattr(registry, "cost", None) is not cost:
            registry.set_cost_ledger(cost)
        # brownout degradation: a ready BrownoutController, or a dict of
        # its kwargs (admission/alerts/metrics wired in here), or None
        if isinstance(brownout, dict):
            brownout = BrownoutController(
                admission=self.admission, alerts=alerts,
                metrics=self.metrics, **brownout)
        self.brownout: Optional[BrownoutController] = brownout
        from deeplearning4j_tpu.observe.health import ServingHealth
        self.health = ServingHealth(registry=registry,
                                    admission=self.admission,
                                    brownout=self.brownout)
        # per-model HTTP request sequence — the serving chaos faults
        # (reject_admission / drop_response) key on it
        self._req_seq: dict = {}
        self._req_seq_lock = threading.Lock()
        self._m_requests = self.metrics.counter(
            "serving_requests_total",
            "Predict requests by model and HTTP status", ("model", "status"))
        self._m_latency = self.metrics.histogram(
            "serving_request_latency_seconds",
            "Predict latency (admission to response)", ("model",))
        self._m_dropped = self.metrics.counter(
            "serving_dropped_responses_total",
            "Responses computed but never delivered (connection severed "
            "— the drop_response chaos fault)", ("model",))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> int:
        """Bind (port 0 → ephemeral) and serve on a background thread;
        returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence
                pass

            # keep-alive connections outlive the listener: track them so
            # stop() can sever idle ones (their handler threads sit in
            # readline() and would otherwise keep serving after shutdown)
            def setup(self):
                super().setup()
                with server._conns_lock:
                    server._conns.add(self.connection)

            def finish(self):
                with server._conns_lock:
                    server._conns.discard(self.connection)
                super().finish()

            # -------------------------------------------------- responders
            # the shared plumbing (observe.metrics.respond): status +
            # exact Content-Length + extra headers + the staged trace
            # correlation headers, whichever branch answered
            def _respond(self, code: int, body: bytes, content_type: str,
                         headers: Tuple[Tuple[str, str], ...] = ()) -> None:
                _respond_http(self, code, body, content_type, headers)

            def _json(self, obj, code: int = 200,
                      headers: Tuple[Tuple[str, str], ...] = ()) -> None:
                _respond_json(self, obj, code, headers)

            # ------------------------------------------------------- GETs
            def do_GET(self):
                # the handler instance persists across keep-alive requests:
                # correlation headers must never leak onto the next response
                self._trace_headers = ()
                parsed = urlparse(self.path)
                path = parsed.path
                if path == "/healthz":
                    self._json({"status": "ok"})
                elif path == "/livez":
                    report = server.health.report()
                    verbose = parse_qs(parsed.query).get("verbose",
                                                         ["0"])[0]
                    body = (report.to_dict()
                            if verbose not in ("0", "", "false")
                            else {"status": report.status})
                    # liveness only fails on a CRITICAL probe (a dead
                    # dispatcher never recovers in-process); degraded
                    # states report 200 with the status in the body
                    self._json(body, 200 if report.healthy else 503)
                elif path == "/alerts":
                    if server.alerts is None:
                        self._json({"error": "no alert manager attached"},
                                   404)
                    else:
                        self._json(server.alerts.describe())
                elif path == "/slo":
                    if server.slo is None:
                        self._json({"error": "no slo config attached"}, 404)
                    else:
                        self._json(server.slo.status(
                            metrics=server.metrics, alerts=server.alerts))
                elif path == "/readyz":
                    ready, body = server.readiness_detail()
                    self._json(body, 200 if ready else 503)
                elif path == "/metrics":
                    self._respond(200, server.metrics.exposition().encode(),
                                  "text/plain; version=0.0.4")
                elif path == "/debug/capture":
                    try:
                        seconds = float(parse_qs(parsed.query).get(
                            "seconds", ["60"])[0])
                    except (TypeError, ValueError):
                        self._json({"error": "seconds must be a number"},
                                   400)
                        return
                    from deeplearning4j_tpu.observe.incident import \
                        capture_bundle
                    tracer = _trace.get_active_tracer()
                    sampler = (tracer.recorder if tracer is not None
                               and hasattr(tracer.recorder, "describe")
                               else None)
                    self._json(capture_bundle(
                        seconds=seconds, tracer=tracer,
                        metrics=server.metrics, cost=server.cost,
                        sampler=sampler))
                elif path == "/v1/models":
                    self._json({"models": server.registry.list_models(),
                                "cost": server.cost.describe()})
                elif path.startswith("/v1/models/"):
                    name = path[len("/v1/models/"):]
                    try:
                        self._json(server.registry.get(name).describe())
                    except ModelNotFound as e:
                        self._json({"error": str(e)}, 404)
                else:
                    self._json({"error": "not found"}, 404)

            # ------------------------------------------------------ predict
            def do_POST(self):
                self._trace_headers = ()  # no stale keep-alive correlation
                # drain the body FIRST, on every path: with HTTP/1.1
                # keep-alive, an unread body on a reject (404/429/503)
                # would desync the connection for the client's next request
                n = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(n)
                path = urlparse(self.path).path
                if not (path.startswith("/v1/models/")
                        and path.endswith("/predict")):
                    self._json({"error": "not found"}, 404)
                    return
                ref = path[len("/v1/models/"):-len("/predict")]
                name, version = server._parse_model_ref(ref)
                server._predict(self, name, version, raw)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self, *, drain: bool = True, drain_timeout_s: float = 5.0,
             shutdown_registry: bool = False) -> None:
        """Graceful shutdown: stop admitting, let in-flight requests finish,
        then close the listener (and optionally the dispatchers)."""
        if drain:
            self.admission.begin_drain()
            self.admission.wait_idle(drain_timeout_s)
        if self.alerts is not None:
            self.alerts.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        # sever surviving keep-alive connections: a persistent client would
        # otherwise keep getting answers from handler threads parked on
        # open sockets after the listener is gone
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if shutdown_registry:
            self.registry.shutdown()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------ internals
    def readiness(self) -> Tuple[bool, str]:
        if self.admission.draining:
            return False, "draining"
        if not self.registry.names():
            return False, "no models registered"
        if self.registry.swapping:
            return False, "hot-swap in progress"
        if not self.registry.healthy():
            return False, "inference dispatcher down"
        if not self.registry.warmed():
            return False, "warmup incomplete"
        return True, "ok"

    def readiness_detail(self) -> Tuple[bool, dict]:
        """``readiness()`` plus the machine-readable why: while bucket
        warmup is still running, the 503 body lists exactly which batch
        buckets would compile if a request hit them now."""
        ready, why = self.readiness()
        body: dict = {"ready": ready, "reason": why}
        if why == "warmup incomplete":
            body["cold_buckets"] = self.registry.cold_buckets()
            errors = self.registry.warmup_errors()
            if errors:  # failed (vs still-running) warmups, and why
                body["warmup_errors"] = errors
        return ready, body

    @staticmethod
    def _parse_model_ref(ref: str) -> Tuple[str, Optional[int]]:
        """``name`` or ``name:version`` (non-numeric suffix = part of the
        name, so names with colons still resolve)."""
        if ":" in ref:
            name, _, tail = ref.rpartition(":")
            try:
                return name, int(tail)
            except ValueError:
                pass
        return ref, None

    def _predict(self, handler, name: str, version: Optional[int],
                 raw: bytes) -> None:
        # join the caller's trace when a traceparent header arrives; echo
        # the trace id either way so the client can correlate
        parent = _trace.parse_traceparent(handler.headers.get("traceparent"))
        tracer = _trace.get_active_tracer()
        if tracer is None:
            if parent is not None:
                handler._trace_headers = (("X-Trace-Id", parent.trace_id),)
            self._predict_timed(handler, name, version, raw)
            return
        with tracer.span("http_request", parent=parent, category="serve",
                         attrs={"model": name}) as sp:
            handler._trace_headers = (
                ("traceparent", sp.context.traceparent()),
                ("X-Trace-Id", sp.trace_id))
            sp.set_attribute(
                "status", self._predict_timed(handler, name, version, raw))

    def _next_seq(self, name: str) -> int:
        """Per-model request sequence (chaos-fault keying). Unknown
        names return -1 and are never counted: the dict's cardinality
        is bounded by the registry's own names — a URL probe must not
        grow server state, the same rule the metric labels follow."""
        if not self.registry.has(name):
            return -1
        with self._req_seq_lock:
            seq = self._req_seq.get(name, 0)
            self._req_seq[name] = seq + 1
            return seq

    @staticmethod
    def _priority(handler) -> int:
        """``X-Priority``: 0 batch, 1 standard (default), 2 interactive —
        garbage parses as standard, never as an error."""
        try:
            return int(handler.headers.get("X-Priority", "1"))
        except (TypeError, ValueError):
            return 1

    @staticmethod
    def _sever(handler) -> None:
        """Close the connection without a response (drop_response)."""
        try:
            handler.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            handler.connection.close()
        except OSError:
            pass
        handler.close_connection = True

    def _retry_headers(self,
                       retry_after_s: Optional[float] = None
                       ) -> Tuple[Tuple[str, str], ...]:
        retry = (retry_after_s if retry_after_s is not None
                 else self.admission.retry_after_s)
        return (("Retry-After", f"{max(retry, 0.001):.3f}"),)

    def _predict_timed(self, handler, name: str, version: Optional[int],
                       raw: bytes) -> int:
        t0 = time.perf_counter()
        status = 500
        dropped = False
        try:
            # serving chaos seam, keyed on (model, request seq). A
            # drop_response fault does all the work below for real but
            # swallows the writes — the connection is severed on the way
            # out, like a network that ate the answer
            seq = self._next_seq(name)
            out = handler
            if seq >= 0 and not _faultinject.on_response(name, seq):
                dropped = True
                out = _DroppedResponder(handler)
            if seq >= 0 and not _faultinject.on_admission(name, seq):
                status = 429
                out._json({"error": "injected admission rejection "
                                    "(chaos fault)"}, 429,
                          headers=self._retry_headers())
                return status
            degrade_to = None
            if self.brownout is not None and self.brownout.observe():
                prio = self._priority(handler)
                if self.brownout.should_shed(prio):
                    status = 429
                    self.admission.record_rejection("brownout")
                    out._json(
                        {"error": f"brownout: shedding priority {prio} "
                                  f"traffic"}, 429,
                        headers=self._retry_headers(
                            self.brownout.retry_after_s))
                    return status
                if self.brownout.degrade and version is None:
                    try:
                        degrade_to = self.registry.resolve_fallback(name)
                    except ModelNotFound:
                        degrade_to = None  # 404s downstream as before
            try:
                slot = self.admission.admit()
            except AdmissionRejected as e:
                status = 429
                out._json(
                    {"error": str(e)}, 429,
                    headers=self._retry_headers(e.retry_after_s))
                return status
            except Draining:
                status = 503
                out._json({"error": "server is draining"}, 503)
                return status
            with slot:
                status = self._predict_admitted(out, name, version, raw,
                                                degrade_to)
            return status
        finally:
            if dropped:
                self._sever(handler)
                self._m_dropped.inc(
                    model=name if self.registry.has(name) else "_unknown")
            # unknown names collapse to one sentinel label — URL probes must
            # not grow the metric registry without bound (same bounded-
            # cardinality rule as the UI server's route labels)
            label = name if self.registry.has(name) else "_unknown"
            self._m_requests.inc(model=label, status=str(status))
            self._m_latency.observe(time.perf_counter() - t0, model=label)

    def _predict_admitted(self, handler, name: str, version: Optional[int],
                          raw: bytes,
                          degrade_to: Optional[int] = None) -> int:
        binary = False
        try:
            content_type = (handler.headers.get("Content-Type") or "").split(
                ";")[0].strip().lower()
            deadline_s = self.default_deadline_s
            hdr = handler.headers.get("X-Deadline-Ms")
            if hdr is not None:
                deadline_s = float(hdr) / 1e3
            if content_type == BINARY_CONTENT_TYPE:
                binary = True
                x = deserialize_array(raw)
            else:
                body = json.loads(raw.decode() or "{}")
                if "inputs" not in body:
                    handler._json({"error": "body needs 'inputs'"}, 400)
                    return 400
                x = np.asarray(body["inputs"], dtype=np.float32)
                if "deadline_ms" in body:
                    deadline_s = float(body["deadline_ms"]) / 1e3
            if x.ndim == 0:
                handler._json({"error": "inputs must be at least 1-d"}, 400)
                return 400
            # brownout: an un-pinned predict degrades to the registry's
            # fallback chain while the brownout holds (the quantized /
            # previous version the operator designated)
            degraded = None
            if degrade_to is not None and version is None:
                served = self.registry.get(name)
                if degrade_to != served.current_version:
                    version = degrade_to
                    degraded = "brownout"
                    self.registry.note_degraded(name, "brownout")
            # version attributed from the model that ACTUALLY served the
            # batch — a hot-swap landing mid-request must not mislabel
            out, v = self.registry.predict_versioned(
                name, x, version=version, deadline_s=deadline_s)
            if degraded is None and version is None:
                # the registry served a breaker failover? the response
                # says so, so a client can tell it was degraded
                state = self.registry.breaker_state(name)
                if state is not None and state != "closed" \
                        and v != self.registry.get(name).current_version:
                    degraded = "breaker"
            extra = (("X-Degraded", degraded),) if degraded else ()
            # bill the request's device-time share HERE, where the
            # priority header is known; dispatcher-served requests get
            # the X-Device-Ms header, synchronous paths (pinned version,
            # canary, degraded) have no ledger entry and no header
            if self.cost is not None:
                trace_id, _ = _trace.current_span_ids()
                device_ms = self.cost.bill(
                    trace_id, model=name,
                    priority=str(self._priority(handler)))
                if device_ms is not None:
                    extra += (("X-Device-Ms", f"{device_ms:.6f}"),)
            if binary:
                handler._respond(200, serialize_array(out),
                                 BINARY_CONTENT_TYPE,
                                 headers=(("X-Model-Version", str(v)),)
                                 + extra)
            else:
                handler._json({"model": name, "version": v,
                               "outputs": np.asarray(out).tolist()},
                              headers=extra)
            return 200
        except ModelNotFound as e:
            handler._json({"error": str(e)}, 404)
            return 404
        except InferenceDeadlineExceeded as e:
            handler._json({"error": str(e)}, 504)
            return 504
        except VersionQuarantined as e:
            # breaker open, nothing to fail over to: back off and retry —
            # the hint is the remaining quarantine cooldown
            handler._json({"error": str(e)}, 503,
                          headers=self._retry_headers(e.retry_after_s))
            return 503
        except DispatcherCrashed as e:
            # transient under supervision (Retry-After = the restart
            # backoff remaining), terminal without — either way a
            # backoff-aware client now gets a concrete hint instead of
            # hammering a dead dispatcher
            handler._json({"error": str(e)}, 503,
                          headers=self._retry_headers(
                              getattr(e, "retry_after_s", None)))
            return 503
        except (ValueError, KeyError, json.JSONDecodeError,
                UnicodeDecodeError, struct.error) as e:
            # struct.error: a truncated binary frame is client garbage, not
            # a model fault — it must land in the 400 bucket
            handler._json({"error": str(e)}, 400)
            return 400
        except Exception as e:  # model raised — contained per request
            handler._json({"error": f"{type(e).__name__}: {e}"}, 500)
            return 500
