"""Admission control: bounded in-flight work, deadlines, graceful drain.

The backpressure seam between the HTTP threads and the batching dispatcher.
Every accepted request holds a slot until its response is written; when all
slots are taken the request is REJECTED immediately with a retry hint (the
429 + ``Retry-After`` path) instead of queueing unboundedly — load sheds at
the front door, so the dispatcher queue can stay small and latency bounded
(the classic admission-control argument: past saturation, added queueing
only converts throughput into latency).

Drain mode is the graceful-shutdown half: new work is refused (503 /
``/readyz`` flips) while in-flight requests finish, then the server can stop
listening with zero dropped responses.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class AdmissionRejected(RuntimeError):
    """Over capacity — shed with a retry hint (HTTP 429)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class Draining(RuntimeError):
    """Shutting down — no new work (HTTP 503)."""


class AdmissionController:
    """Counting-semaphore admission with drain support.

    ``max_inflight`` bounds concurrently admitted requests;
    ``retry_after_s`` is the hint handed back on overflow (a fraction of the
    typical batch window is a sane default — the queue turns over quickly).
    """

    def __init__(self, max_inflight: int = 64, *, retry_after_s: float = 1.0,
                 metrics=None):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = int(max_inflight)
        self.retry_after_s = float(retry_after_s)
        self._inflight = 0
        self._draining = False
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._m_inflight = self._m_rejected = None
        if metrics is not None:
            self._m_inflight = metrics.gauge(
                "serving_inflight_requests",
                "Requests admitted and not yet answered")
            self._m_rejected = metrics.counter(
                "serving_admission_rejections_total",
                "Requests shed at admission", ("reason",))

    # ------------------------------------------------------------ admission
    def admit(self) -> "_Slot":
        """Take a slot or raise ``AdmissionRejected`` / ``Draining``.
        Use as a context manager: ``with ctrl.admit(): ...``."""
        with self._lock:
            if self._draining:
                if self._m_rejected is not None:
                    self._m_rejected.inc(reason="draining")
                raise Draining("server is draining")
            if self._inflight >= self.max_inflight:
                if self._m_rejected is not None:
                    self._m_rejected.inc(reason="overflow")
                raise AdmissionRejected(
                    f"{self._inflight} requests in flight "
                    f"(limit {self.max_inflight})", self.retry_after_s)
            self._inflight += 1
            if self._m_inflight is not None:
                self._m_inflight.set(self._inflight)
        return _Slot(self)

    def _release(self) -> None:
        with self._lock:
            self._inflight -= 1
            if self._m_inflight is not None:
                self._m_inflight.set(self._inflight)
            if self._inflight == 0:
                self._idle.notify_all()

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def record_rejection(self, reason: str) -> None:
        """Count a shed decision made OUTSIDE the slot machinery (the
        brownout controller rejects at the front door without ever
        taking a slot) in the same rejection series."""
        if self._m_rejected is not None:
            self._m_rejected.inc(reason=reason)

    # -------------------------------------------------------------- drain
    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until every admitted request released its slot."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._inflight > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return True


class _Slot:
    """RAII handle for one admitted request."""

    __slots__ = ("_ctrl", "_released")

    def __init__(self, ctrl: AdmissionController):
        self._ctrl = ctrl
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ctrl._release()

    def __enter__(self) -> "_Slot":
        return self

    def __exit__(self, *exc) -> None:
        self.release()
