"""Versioned model registry — the control plane of the serving tier.

Each registered name owns a monotonically-versioned history and ONE
``ParallelInference`` dispatcher; activating a version is an atomic
``ParallelInference.update_model`` hot-swap (in-flight batches finish on the
old weights, the next coalesced batch runs the new ones — no request ever
sees a torn model), and ``rollback`` re-activates the previously live
version. Models load from every source the framework already speaks:

- a live model object (trained in-process, zoo-built, Keras-imported);
- a path, routed through ``util.model_guesser.load_model_guess`` — own
  ModelSerializer zips, reference DL4J checkpoints, Keras HDF5.

This is the role of the reference's model-server deployments around
``ParallelInference.java`` (dl4j-streaming pumping fresh checkpoints into a
running model), made explicit as an API.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.parallel.inference import ParallelInference


class ModelNotFound(KeyError):
    """Unknown model name or version (the HTTP 404 path)."""


class ModelVersion:
    """One immutable registry entry."""

    __slots__ = ("version", "model", "source", "registered_at")

    def __init__(self, version: int, model, source: str):
        self.version = version
        self.model = model
        self.source = source
        self.registered_at = time.time()


class ServedModel:
    """A name + its version history + the live batching dispatcher."""

    def __init__(self, name: str, inference: ParallelInference):
        self.name = name
        self.inference = inference
        self.versions: Dict[int, ModelVersion] = {}
        self.current_version: Optional[int] = None
        self.previous_version: Optional[int] = None

    def describe(self) -> dict:
        return {
            "name": self.name,
            "current_version": self.current_version,
            "previous_version": self.previous_version,
            "healthy": self.inference.healthy,
            "versions": [
                {"version": v.version, "source": v.source,
                 "registered_at": v.registered_at}
                for v in sorted(self.versions.values(),
                                key=lambda m: m.version)],
        }


class ModelRegistry:
    """Thread-safe registry; one ``ParallelInference`` per model name.

    ``metrics`` is an ``observe.metrics.MetricsRegistry`` (duck-typed) shared
    with the dispatchers — swap/rollback events and per-model live-version
    gauges land next to the batch/queue series the dispatchers emit.
    """

    def __init__(self, *, metrics=None, max_batch_size: int = 32,
                 queue_limit: int = 64, wait_ms: float = 2.0, mesh=None):
        self._models: Dict[str, ServedModel] = {}
        self._lock = threading.RLock()
        self._swap_lock = threading.Lock()  # serializes hot-swaps
        self._metrics = metrics
        self._pi_kw = dict(max_batch_size=max_batch_size,
                           queue_limit=queue_limit, wait_ms=wait_ms,
                           mesh=mesh)
        self._swapping = 0  # >0 while a hot-swap is in progress (readiness)
        self._m_swaps = self._m_version = None
        if metrics is not None:
            self._m_swaps = metrics.counter(
                "serving_model_swaps_total",
                "Hot-swap activations (including rollbacks)",
                ("model", "kind"))
            self._m_version = metrics.gauge(
                "serving_model_version", "Currently live version", ("model",))

    # ------------------------------------------------------------- loading
    @staticmethod
    def load(path: str):
        """Load a model of unknown provenance (ModelGuesser order: own MLN
        zip → own CG zip → DL4J MLN/CG checkpoint → Keras h5)."""
        from deeplearning4j_tpu.util.model_guesser import load_model_guess
        return load_model_guess(str(path))

    # ------------------------------------------------------------ mutation
    def register(self, name: str, model=None, *, path: Optional[str] = None,
                 activate: bool = True) -> int:
        """Register a new version of ``name``; returns the version number.

        Exactly one of ``model`` (a live object) or ``path`` (anything
        ``load_model_guess`` accepts) must be given. The first version of a
        name activates unconditionally; later ones only when ``activate``.
        """
        if (model is None) == (path is None):
            raise ValueError("register() needs exactly one of model=/path=")
        source = "object"
        if path is not None:
            model = self.load(path)
            source = str(path)
        with self._lock:
            served = self._models.get(name)
            if served is None:
                served = ServedModel(
                    name, ParallelInference(
                        model, mode="batched", metrics=self._metrics,
                        metrics_name=name, **self._pi_kw))
                self._models[name] = served
                version = 1
                served.versions[version] = ModelVersion(version, model, source)
                served.current_version = version
                self._note_swap(name, version, "register")
                return version
            version = max(served.versions) + 1
            served.versions[version] = ModelVersion(version, model, source)
        if activate:
            self.activate(name, version)
        return version

    def activate(self, name: str, version: int, *,
                 _kind: str = "activate") -> None:
        """Atomic hot-swap of the live version (rollback's forward twin).
        Activations are serialized by ``_swap_lock`` so the dispatcher's
        live model can never disagree with ``current_version`` when two
        publishers race."""
        with self._swap_lock:
            with self._lock:
                served = self._get(name)
                if version not in served.versions:
                    raise ModelNotFound(f"{name} has no version {version}")
                if version == served.current_version:
                    return
                self._swapping += 1
            try:
                # the swap itself is atomic inside ParallelInference; the
                # _swapping counter only widens the readiness signal around it
                served.inference.update_model(served.versions[version].model)
                with self._lock:
                    served.previous_version = served.current_version
                    served.current_version = version
                    self._note_swap(name, version, _kind)
            finally:
                with self._lock:
                    self._swapping -= 1

    def rollback(self, name: str) -> int:
        """Re-activate the previously live version; returns it. Counts as
        ONE swap event (kind=rollback) — summing the swap counter over
        kinds must equal the number of swaps."""
        with self._lock:
            served = self._get(name)
            prev = served.previous_version
            if prev is None:
                raise ModelNotFound(f"{name} has no previous version")
        self.activate(name, prev, _kind="rollback")
        return prev

    def _note_swap(self, name: str, version: int, kind: str) -> None:
        if self._m_swaps is not None:
            self._m_swaps.inc(model=name, kind=kind)
        if self._m_version is not None:
            self._m_version.set(version, model=name)

    # ------------------------------------------------------------- queries
    def _get(self, name: str) -> ServedModel:
        served = self._models.get(name)
        if served is None:
            raise ModelNotFound(f"no model named {name!r}")
        return served

    def get(self, name: str) -> ServedModel:
        with self._lock:
            return self._get(name)

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._models

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def list_models(self) -> List[dict]:
        with self._lock:
            return [self._models[n].describe() for n in sorted(self._models)]

    @property
    def swapping(self) -> bool:
        with self._lock:
            return self._swapping > 0

    def healthy(self) -> bool:
        """Every dispatcher alive (readiness ingredient)."""
        with self._lock:
            return all(m.inference.healthy for m in self._models.values())

    # ------------------------------------------------------------ data path
    def predict(self, name: str, x, *, version: Optional[int] = None,
                deadline_s: Optional[float] = None):
        """Predict through the live dispatcher; see ``predict_versioned``."""
        return self.predict_versioned(name, x, version=version,
                                      deadline_s=deadline_s)[0]

    def predict_versioned(self, name: str, x, *,
                          version: Optional[int] = None,
                          deadline_s: Optional[float] = None):
        """Predict; returns ``(outputs, version_served)``.

        A pinned ``version`` that is not the live one runs synchronously on
        that version's model (no batching) — the escape hatch for canarying
        an old/new version side by side; the live version always goes
        through the coalescing dispatcher. ``version_served`` is attributed
        from the model object that ACTUALLY served the batch, so a hot-swap
        landing mid-request can never mislabel an old model's output with
        the new version number.
        """
        served = self.get(name)
        with self._lock:
            current = served.current_version
            if version is not None and version not in served.versions:
                raise ModelNotFound(f"{name} has no version {version}")
            pinned = (served.versions[version].model
                      if version is not None and version != current else None)
        if pinned is not None:
            import numpy as np
            return np.asarray(pinned.output(np.asarray(x))), version
        out, model = served.inference.output(x, deadline_s=deadline_s,
                                             return_model=True)
        with self._lock:
            ver = next((mv.version for mv in served.versions.values()
                        if mv.model is model), served.current_version)
        return out, ver

    # ----------------------------------------------------------- lifecycle
    def shutdown(self) -> None:
        """Stop every dispatcher (flushes in-flight batches first)."""
        with self._lock:
            models = list(self._models.values())
        for m in models:
            m.inference.shutdown()
