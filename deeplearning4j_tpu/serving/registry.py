"""Versioned model registry — the control plane of the serving tier.

Each registered name owns a monotonically-versioned history and ONE
``ParallelInference`` dispatcher; activating a version is an atomic
``ParallelInference.update_model`` hot-swap (in-flight batches finish on the
old weights, the next coalesced batch runs the new ones — no request ever
sees a torn model), and ``rollback`` re-activates the previously live
version. Models load from every source the framework already speaks:

- a live model object (trained in-process, zoo-built, Keras-imported);
- a path, routed through ``util.model_guesser.load_model_guess`` — own
  ModelSerializer zips, reference DL4J checkpoints, Keras HDF5.

This is the role of the reference's model-server deployments around
``ParallelInference.java`` (dl4j-streaming pumping fresh checkpoints into a
running model), made explicit as an API.

Serving fast path (round 9): registration is where serving pays its
one-time costs, so no live request ever does —

- **AOT bucket warmup**: every declared batch bucket's forward is executed
  (and therefore XLA-compiled) at ``register`` time, for EVERY version —
  including not-yet-active ones — so a later hot-swap or rollback lands on
  an already-compiled forward. ``warmup="sync"`` blocks registration until
  warm; ``"async"`` warms on a background thread while ``/readyz`` reports
  the cold buckets; ``"off"`` restores the old lazy behavior.
  ``serving_warmup_seconds{model}`` and ``serving_buckets_warm{model}``
  expose the state.
- **persistent compile cache**: ``compile_cache_dir=`` points JAX's
  compilation cache at disk, so a restarted server (or a rollback to an
  architecture compiled last week) warms from cache instead of compiling.
- **dtype policy**: ``register(..., dtype_policy="int8"|"bf16")`` serves a
  weight-quantized wrapper of the version (``serving/quantize.py``),
  calibrated against ``sample_input`` at registration; the quantization
  error is recorded on the version and can gate registration
  (``quant_tolerance``).

Canary deploys (the ``pipeline/`` subsystem's data plane):

- **weighted routing**: ``set_traffic_split(name, {version: fraction})``
  gives non-live versions deterministic fractions of un-pinned ``predict``
  traffic (smooth weighted round-robin — no RNG, so tests and replays see
  exact request counts); the live version serves the remainder through
  the batching dispatcher.  The split is warm-gated: a version whose AOT
  bucket warmup has not finished (or failed) is refused a fraction, so a
  canary never puts a cold forward in front of traffic.
  ``serving_canary_fraction{model,version}`` exports the live split
  (cardinality bounded by the registry's own version history — one series
  per version ever canaried, zeroed when the split clears).
- **shadow mode**: ``set_shadow(name, version, sample=...)`` duplicates
  every Nth live request to the candidate OFF the response path (a
  bounded background queue; overflow drops the sample, never the
  response) and diffs the outputs: ``shadow_requests_total{model}`` /
  ``shadow_divergence_total{model}`` count the comparisons and the
  out-of-tolerance ones, and a bounded in-memory divergence log keeps the
  worst offenders for inspection.  Any hot-swap (promote, rollback)
  clears both the split and the shadow — a new live version invalidates
  the experiment.

Serving resilience (round 13): the data plane self-heals —

- **dispatcher supervision**: ``max_dispatcher_restarts`` lets a crashed
  batching dispatcher restart in place under the elastic backoff ladder
  (``ParallelInference`` does the restarting; the registry just wires the
  budget and the injectable clock through), so a single poisoned batch no
  longer kills the name until a human intervenes.
- **per-version circuit breakers** (``serving/breaker.py``): with
  ``breaker=dict(...)`` every registered version gets a
  closed→open→half-open breaker fed by forward crashes. A version that
  keeps crashing the dispatcher is quarantined (its siblings keep the
  restart budget) and un-pinned traffic fails over to the
  **fallback chain** — ``set_fallback(name, ["previous"])`` or explicit
  version numbers (e.g. the int8 policy variant registered alongside) —
  until the half-open probe proves the forward healthy again.
  ``serving_breaker_state{model,version}`` (0/1/2) and
  ``serving_degraded_requests_total{model,reason}`` journal every move.
- **failover on crash**: any un-pinned request that loses its dispatcher
  mid-flight is re-served on the fallback chain instead of surfacing a
  503, when a chain is designated — the acceptance bar for the chaos
  tests is *zero client-visible 5xx after the breaker trips*.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.parallel.inference import (
    DispatcherCrashed, InferenceDeadlineExceeded, ParallelInference)
from deeplearning4j_tpu.serving import breaker as _breaker
from deeplearning4j_tpu.serving import quantize as _quantize


class ModelNotFound(KeyError):
    """Unknown model name or version (the HTTP 404 path)."""


class VersionQuarantined(RuntimeError):
    """The live version's circuit breaker is open and the fallback chain
    resolved to nothing servable — the 503 + ``Retry-After`` path.
    ``retry_after_s`` hints when the quarantine could lift."""

    def __init__(self, msg: str, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ModelVersion:
    """One immutable registry entry. ``model`` is the object that SERVES
    (the quantized wrapper under a non-float32 ``dtype_policy``);
    ``quant_error`` carries the calibration stats when a sample batch was
    provided at registration."""

    __slots__ = ("version", "model", "source", "registered_at",
                 "dtype_policy", "quant_error", "mesh")

    def __init__(self, version: int, model, source: str,
                 dtype_policy: str = "float32",
                 quant_error: Optional[dict] = None, mesh=None):
        self.version = version
        self.model = model
        self.source = source
        self.registered_at = time.time()
        self.dtype_policy = dtype_policy
        self.quant_error = quant_error
        # the jax Mesh this version's params are placed on (None =
        # replicated/single-device); activation repoints the dispatcher's
        # batch sharding at it
        self.mesh = mesh


class ServedModel:
    """A name + its version history + the live batching dispatcher."""

    def __init__(self, name: str, inference: ParallelInference):
        self.name = name
        self.inference = inference
        self.versions: Dict[int, ModelVersion] = {}
        # monotonic high-water mark: version numbers are never reused,
        # even after unregister() — journals and per-version metric
        # series must never conflate two different candidates
        self.next_version = 1
        self.current_version: Optional[int] = None
        self.previous_version: Optional[int] = None
        # version -> warmup state:
        #   {"status": "pending"|"warming"|"warm"|"skipped"|"error",
        #    "buckets": [declared], "warm": [done], "seconds": float,
        #    "reason": str|None}
        self.warmup_state: Dict[int, dict] = {}
        # version -> resolved (row_shape, dtype) spec, kept so rewarm()
        # can re-run a failed warmup without re-resolving the model
        self.warmup_spec: Dict[int, Optional[tuple]] = {}
        # canary data plane: non-live version -> traffic fraction, plus
        # the smooth-WRR accumulators that make routing deterministic
        self.traffic_split: Dict[int, float] = {}
        self._wrr_acc: Dict[int, float] = {}
        # shadow experiment state (None when off); mutated under the
        # registry lock, read by the shadow worker
        self.shadow: Optional[dict] = None
        # resilience: one breaker per version (when enabled) and the
        # registry-designated fallback chain — version numbers and/or
        # "previous", resolved in order at failover time
        self.breakers: Dict[int, "_breaker.CircuitBreaker"] = {}
        self.fallbacks: List[object] = []

    def pick_weighted(self) -> int:
        """Smooth weighted round-robin over {current + split versions}.
        Called under the registry lock.  Deterministic: each version's
        accumulator grows by its weight every request; the largest
        accumulator serves and pays 1.  Ties break toward the heavier
        weight (the live version on an even split), then the lower
        version — no RNG anywhere, so a 0.25 split serves exactly 1 of
        every 4 requests from the canary."""
        weights = dict(self.traffic_split)
        weights[self.current_version] = max(
            0.0, 1.0 - sum(self.traffic_split.values()))
        for v, w in weights.items():
            self._wrr_acc[v] = self._wrr_acc.get(v, 0.0) + w
        chosen = max(weights,
                     key=lambda v: (self._wrr_acc[v], weights[v], -v))
        self._wrr_acc[chosen] -= 1.0
        return chosen

    def describe(self) -> dict:
        def _ver(v: ModelVersion) -> dict:
            d = {"version": v.version, "source": v.source,
                 "registered_at": v.registered_at,
                 "dtype_policy": v.dtype_policy}
            if v.quant_error is not None:
                d["quant_error"] = v.quant_error
            if v.mesh is not None:  # GSPMD placement is operator-visible
                d["mesh"] = {k: int(s) for k, s in v.mesh.shape.items()}
            w = self.warmup_state.get(v.version)
            if w is not None:
                d["warmup"] = dict(w)
            return d

        d = {
            "name": self.name,
            "current_version": self.current_version,
            "previous_version": self.previous_version,
            "healthy": self.inference.healthy,
            "versions": [_ver(v) for v in sorted(self.versions.values(),
                                                 key=lambda m: m.version)],
        }
        # a canary in flight is operator-visible: the /v1/models payload
        # carries the live split and the shadow experiment's counters
        if self.traffic_split:
            d["traffic"] = [{"version": v, "fraction": f}
                            for v, f in sorted(self.traffic_split.items())]
        if self.shadow is not None:
            s = self.shadow
            d["shadow"] = {"version": s["version"], "sample": s["sample"],
                           "requests": s["requests"],
                           "divergences": s["divergences"],
                           "dropped": s["dropped"]}
        if self.fallbacks:
            d["fallbacks"] = list(self.fallbacks)
        tripped = {str(v): br.state for v, br in sorted(self.breakers.items())
                   if br.state != _breaker.CLOSED}
        if tripped:  # a quarantine in flight is operator-visible
            d["breakers"] = tripped
        return d


class ModelRegistry:
    """Thread-safe registry; one ``ParallelInference`` per model name.

    ``metrics`` is an ``observe.metrics.MetricsRegistry`` (duck-typed) shared
    with the dispatchers — swap/rollback events and per-model live-version
    gauges land next to the batch/queue series the dispatchers emit.
    """

    def __init__(self, *, metrics=None, max_batch_size: int = 32,
                 queue_limit: int = 64, wait_ms: float = 2.0, mesh=None,
                 buckets: Optional[Sequence[int]] = None,
                 warmup: str = "sync",
                 compile_cache_dir: Optional[str] = None,
                 max_dispatcher_restarts: int = 0,
                 restart_backoff=None,
                 breaker: Optional[dict] = None,
                 time_source=None, cost=None):
        """Resilience knobs (round 13): ``max_dispatcher_restarts`` lets
        each name's crashed dispatcher restart in place (0 keeps the
        terminal-crash contract); ``restart_backoff`` is an elastic
        ``BackoffPolicy``; ``breaker=dict(failure_threshold=, window_s=,
        cooldown_s=, half_open_probes=)`` arms a per-version circuit
        breaker (None = off); ``time_source`` (a
        ``parallel.time_source.TimeSource``) drives breaker cooldowns AND
        restart backoff so chaos tests run on a manual clock."""
        if warmup not in ("sync", "async", "off"):
            raise ValueError(f"warmup must be sync|async|off, got {warmup!r}")
        if compile_cache_dir is not None:
            from deeplearning4j_tpu.util.compile_cache import (
                enable_persistent_compile_cache)
            enable_persistent_compile_cache(compile_cache_dir)
        self._models: Dict[str, ServedModel] = {}
        self._lock = threading.RLock()
        self._swap_lock = threading.Lock()  # serializes hot-swaps
        self._metrics = metrics
        self._time_source = time_source
        restart_clock = (time.monotonic if time_source is None else
                         lambda: time_source.current_time_millis() / 1e3)
        # optional observe.cost.CostLedger shared by every dispatcher:
        # per-request device-time attribution (the /v1/models cost block
        # and the X-Device-Ms header read it)
        self.cost = cost
        self._pi_kw = dict(max_batch_size=max_batch_size,
                           queue_limit=queue_limit, wait_ms=wait_ms,
                           mesh=mesh, buckets=buckets,
                           max_restarts=int(max_dispatcher_restarts),
                           restart_clock=restart_clock, cost=cost)
        if restart_backoff is not None:
            self._pi_kw["restart_backoff"] = restart_backoff
        self._breaker_kw = dict(breaker) if breaker is not None else None
        if self._breaker_kw is not None:
            # fail fast on a typo'd knob, not at first registration
            _breaker.CircuitBreaker(time_source=time_source,
                                    **self._breaker_kw)
        self._warmup_mode = warmup
        self._swapping = 0  # >0 while a hot-swap is in progress (readiness)
        self._m_swaps = self._m_version = None
        self._m_warm_s = self._m_warm_n = None
        self._m_canary = self._m_shadow_req = self._m_shadow_div = None
        self._m_breaker = self._m_degraded = None
        # shadow worker: ONE daemon + bounded queue per registry, started
        # lazily; overflow drops the shadow sample, never the response
        self._shadow_queue: "deque" = deque()
        self._shadow_cv = threading.Condition()
        self._shadow_inflight = 0
        self._shadow_stop = False
        self._shadow_thread: Optional[threading.Thread] = None
        if metrics is not None:
            self._m_swaps = metrics.counter(
                "serving_model_swaps_total",
                "Hot-swap activations (including rollbacks)",
                ("model", "kind"))
            self._m_version = metrics.gauge(
                "serving_model_version", "Currently live version", ("model",))
            self._m_warm_s = metrics.gauge(
                "serving_warmup_seconds",
                "Wall seconds the last registration spent pre-compiling "
                "batch buckets", ("model",))
            self._m_warm_n = metrics.gauge(
                "serving_buckets_warm",
                "Batch buckets of the LIVE version already compiled "
                "(requests on them never trigger XLA)", ("model",))
            self._m_canary = metrics.gauge(
                "serving_canary_fraction",
                "Traffic fraction routed to a non-live version "
                "(0 when the split is cleared)", ("model", "version"))
            self._m_shadow_req = metrics.counter(
                "shadow_requests_total",
                "Live requests duplicated to a shadow candidate",
                ("model",))
            self._m_shadow_div = metrics.counter(
                "shadow_divergence_total",
                "Shadow comparisons whose output diverged past the "
                "configured threshold", ("model",))
            self._m_breaker = metrics.gauge(
                "serving_breaker_state",
                "Per-version circuit breaker: 0 closed, 1 open "
                "(quarantined), 2 half-open (probing). Cardinality "
                "bounded by the registry's own version history",
                ("model", "version"))
            self._m_degraded = metrics.counter(
                "serving_degraded_requests_total",
                "Requests served on a fallback/degraded version instead "
                "of the one that should have served them",
                ("model", "reason"))

    # ------------------------------------------------------------- loading
    @staticmethod
    def load(path: str):
        """Load a model of unknown provenance (ModelGuesser order: own MLN
        zip → own CG zip → DL4J MLN/CG checkpoint → Keras h5)."""
        from deeplearning4j_tpu.util.model_guesser import load_model_guess
        return load_model_guess(str(path))

    # ------------------------------------------------------------ mutation
    def register(self, name: str, model=None, *, path: Optional[str] = None,
                 activate: bool = True, dtype_policy: str = "float32",
                 sample_input=None, input_shape: Optional[Sequence[int]] = None,
                 quant_tolerance: Optional[float] = None,
                 mesh=None, sharding_rules=None) -> int:
        """Register a new version of ``name``; returns the version number.

        Exactly one of ``model`` (a live object) or ``path`` (anything
        ``load_model_guess`` accepts) must be given. The first version of a
        name activates unconditionally; later ones only when ``activate``
        — and under ``warmup="async"`` the activation happens when the new
        version's warmup COMPLETES (the hot-swap must land on an already-
        compiled forward, never put a cold version in front of traffic).

        ``dtype_policy``: serve this version ``"float32"`` (as-is),
        ``"bf16"`` or ``"int8"`` (weight-quantized wrapper; see
        ``serving/quantize.py``). With a non-float policy and a
        ``sample_input`` batch, the quantized output is calibrated against
        the float one and the deviation recorded on the version
        (``quant_tolerance`` rejects the registration past that relative
        error).

        Warmup input spec resolution, per version: ``input_shape`` (a
        per-row feature shape) > ``sample_input``'s row shape > the conf's
        ``InputType`` > the first layer's ``n_in``. A model yielding no
        spec (duck-typed stubs) skips warmup and is treated as warm.

        ``mesh`` serves this version GSPMD-sharded: params are placed by
        ``sharding_rules`` (default: the Megatron 2-D rule set) over the
        mesh, warmup batches ship data-axis-sharded to the same device
        set, and activation repoints the dispatcher's batch sharding at
        this mesh. ``float32`` only (a quantized wrapper's packed params
        do not go through the rule matcher). Canary/shadow splits across
        versions on DIFFERENT device sets are not supported — activate
        the sharded version outright.
        """
        if (model is None) == (path is None):
            raise ValueError("register() needs exactly one of model=/path=")
        if dtype_policy not in _quantize.DTYPE_POLICIES:
            raise ValueError(f"unknown dtype_policy {dtype_policy!r} "
                             f"(one of {_quantize.DTYPE_POLICIES})")
        if mesh is not None and dtype_policy != "float32":
            raise ValueError(
                "mesh= (GSPMD-sharded serving) requires dtype_policy="
                f"'float32', got {dtype_policy!r}")
        source = "object"
        if path is not None:
            model = self.load(path)
            source = str(path)
        if mesh is not None:
            from deeplearning4j_tpu.parallel.sharding import (
                shard_model_with_rules)
            shard_model_with_rules(model, mesh, sharding_rules)
        quant_error = None
        served_obj = model
        if dtype_policy != "float32":
            served_obj = _quantize.quantize_model(model, dtype_policy)
            if sample_input is not None:
                quant_error = _quantize.calibrate(model, served_obj,
                                                  sample_input)
                _quantize.check_tolerance(quant_error, quant_tolerance)
            if path is not None:
                # registry-owned checkpoint load: nobody else references
                # the float model, so don't pin a full float param copy
                # next to the quantized one for the version's lifetime
                served_obj.release_base_params()
        first = False
        with self._lock:
            served = self._models.get(name)
            if served is None:
                first = True
                pi_kw = dict(self._pi_kw)
                if mesh is not None:
                    # the dispatcher is born on the version's mesh so
                    # buckets round to ITS data axis from the start
                    pi_kw["mesh"] = mesh
                served = ServedModel(
                    name, ParallelInference(
                        served_obj, mode="batched", metrics=self._metrics,
                        metrics_name=name, **pi_kw))
                self._models[name] = served
            version = served.next_version
            served.next_version += 1
            served.versions[version] = ModelVersion(
                version, served_obj, source, dtype_policy=dtype_policy,
                quant_error=quant_error, mesh=mesh)
            if self._breaker_kw is not None:
                served.breakers[version] = _breaker.CircuitBreaker(
                    time_source=self._time_source,
                    name=f"{name}:v{version}", **self._breaker_kw)
                if self._m_breaker is not None:
                    self._m_breaker.set(0, model=name,
                                        version=str(version))
            if first:
                served.current_version = version
                self._note_swap(name, version, "register")
        spec = self._resolve_row_spec(served_obj, input_shape, sample_input)
        # async warmup + activate: the swap must land on an already-
        # compiled forward, so the warmup thread activates when it's warm
        # (on warmup FAILURE the old version keeps serving — rewarm() then
        # activate() is the recovery path)
        defer = (not first and activate and spec is not None
                 and self._warmup_mode == "async")
        self._begin_warmup(served, version, spec, activate_after=defer)
        if not first and activate and not defer:
            self.activate(name, version)
        if first:
            with self._lock:
                self._update_warm_gauge(served)
        return version

    # ------------------------------------------------------------- warmup
    def _resolve_row_spec(self, model, input_shape,
                          sample_input) -> Optional[Tuple[tuple, object]]:
        """(row_shape, host dtype) to warm with, or None (skip warmup)."""
        if input_shape is not None:
            return tuple(int(s) for s in input_shape), np.float32
        if sample_input is not None:
            s = np.asarray(sample_input)
            if s.ndim >= 1:
                # warm with the HOST dtype requests actually arrive in —
                # the JSON path parses to float32 regardless of model
                # dtype, and np.random/np.array default to float64, which
                # no wire format ships: warming '<f8' would leave the live
                # '<f4' signature cold (and falsely alarm the cold counter)
                dt = s.dtype if (np.issubdtype(s.dtype, np.floating)
                                 and s.dtype != np.float64) else np.float32
                return tuple(s.shape[1:]), dt
        conf = getattr(model, "conf", None)
        if conf is None:
            return None
        it = getattr(conf, "input_type", None)
        if it is not None:
            return tuple(it.batch_shape(1)[1:]), np.float32
        # single-input graph with a declared InputType
        input_types = getattr(conf, "input_types", None)
        inputs = getattr(conf, "inputs", None)
        if (input_types and inputs and len(inputs) == 1
                and input_types[0] is not None):
            return tuple(input_types[0].batch_shape(1)[1:]), np.float32
        layers = getattr(conf, "layers", None)
        if layers:
            n_in = getattr(layers[0], "n_in", None)
            if n_in:
                return (int(n_in),), np.float32
        return None

    def _begin_warmup(self, served: ServedModel, version: int,
                      spec: Optional[Tuple[tuple, object]],
                      activate_after: bool = False) -> None:
        declared = list(served.inference.buckets)
        served.warmup_spec[version] = spec
        if self._warmup_mode == "off" or spec is None:
            with self._lock:
                served.warmup_state[version] = {
                    "status": "skipped", "buckets": declared, "warm": [],
                    "seconds": 0.0,
                    "reason": ("warmup disabled"
                               if self._warmup_mode == "off"
                               else "no input spec (pass input_shape= or "
                                    "sample_input=)")}
            return
        with self._lock:
            served.warmup_state[version] = {
                "status": "pending", "buckets": declared, "warm": [],
                "seconds": 0.0, "reason": None}
        if self._warmup_mode == "sync":
            self._run_warmup(served, version, spec, activate_after)
        else:
            threading.Thread(target=self._run_warmup,
                             args=(served, version, spec, activate_after),
                             name=f"warmup-{served.name}-v{version}",
                             daemon=True).start()

    def _run_warmup(self, served: ServedModel, version: int,
                    spec: Tuple[tuple, object],
                    activate_after: bool = False) -> None:
        row_shape, dtype = spec
        state = served.warmup_state[version]
        model = served.versions[version].model
        # a version placed on its own mesh warms with ITS batch sharding,
        # not the dispatcher's current one (they differ until activation)
        vmesh = served.versions[version].mesh
        state["status"] = "warming"
        t0 = time.perf_counter()
        try:
            for b in state["buckets"]:
                served.inference.warmup(row_shape, dtype=dtype, model=model,
                                        buckets=[b], mesh=vmesh)
                with self._lock:
                    state["warm"].append(b)
                    self._update_warm_gauge(served)
            with self._lock:
                state["status"] = "warm"
                state["seconds"] = round(time.perf_counter() - t0, 4)
                if self._m_warm_s is not None:
                    self._m_warm_s.set(state["seconds"], model=served.name)
        except Exception as e:  # noqa: BLE001 — a warmup failure must not
            # take the registry down; the version stays cold and /readyz
            # says why
            with self._lock:
                state["status"] = "error"
                state["reason"] = f"{type(e).__name__}: {e}"
                state["seconds"] = round(time.perf_counter() - t0, 4)
                if activate_after:
                    state["reason"] += ("; deferred activation skipped — "
                                        "previous version keeps serving")
            return
        if activate_after:
            try:
                self.activate(served.name, version)
            except Exception as e:  # noqa: BLE001 — surfaced, not fatal
                with self._lock:
                    state["reason"] = (f"warm, but deferred activation "
                                       f"failed: {type(e).__name__}: {e}")

    def _update_warm_gauge(self, served: ServedModel) -> None:
        if self._m_warm_n is None:
            return
        state = served.warmup_state.get(served.current_version)
        if state is None:
            return
        n = (len(state["buckets"]) if state["status"] == "skipped"
             else len(state["warm"]))
        self._m_warm_n.set(n, model=served.name)

    def cold_buckets(self) -> Dict[str, List[int]]:
        """Per model: declared buckets of the LIVE version not yet warmed
        (empty when warm, skipped, or warmup disabled). The ``/readyz``
        payload."""
        out: Dict[str, List[int]] = {}
        with self._lock:
            for name, served in self._models.items():
                state = served.warmup_state.get(served.current_version)
                if state is None or state["status"] == "skipped":
                    continue
                cold = [b for b in state["buckets"]
                        if b not in state["warm"]]
                if cold:
                    out[name] = cold
        return out

    def warmed(self) -> bool:
        """True when no live version still has cold buckets."""
        return not self.cold_buckets()

    def warmup_errors(self) -> Dict[str, str]:
        """Per model: the error reason when the LIVE version's warmup
        FAILED (readyz surfaces this next to the cold buckets, so an
        operator can tell a crashed warmup from one still running)."""
        out: Dict[str, str] = {}
        with self._lock:
            for name, served in self._models.items():
                state = served.warmup_state.get(served.current_version)
                if state is not None and state["status"] == "error":
                    out[name] = state["reason"] or "warmup failed"
        return out

    def rewarm(self, name: str, version: Optional[int] = None) -> int:
        """Re-run bucket warmup for ``version`` (default: live) — the
        recovery path when registration-time warmup errored (transient
        OOM, device hiccup) and the process should become ready without
        a restart. Returns the version warmed."""
        with self._lock:
            served = self._get(name)
            v = served.current_version if version is None else version
            if v not in served.versions:
                raise ModelNotFound(f"{name} has no version {v}")
            spec = served.warmup_spec.get(v)
        self._begin_warmup(served, v, spec)
        with self._lock:
            self._update_warm_gauge(served)
        return v

    def warmup_state(self, name: str,
                     version: Optional[int] = None) -> dict:
        """The warmup record of ``version`` (default: live) of ``name``."""
        with self._lock:
            served = self._get(name)
            v = served.current_version if version is None else version
            state = served.warmup_state.get(v)
            return dict(state) if state is not None else {"status": "unknown"}

    def unregister(self, name: str, version: int) -> None:
        """Retire a non-live version: drop it (and its warmup state, any
        traffic fraction, any shadow experiment on it) from the registry
        so a long-running pipeline does not accumulate one full model per
        rejected candidate. The LIVE version is refused; retiring the
        previous version clears the rollback target."""
        with self._lock:
            served = self._get(name)
            if version not in served.versions:
                raise ModelNotFound(f"{name} has no version {version}")
            if version == served.current_version:
                raise ValueError(
                    f"{name} v{version} is the live version; activate "
                    "another version before unregistering it")
            if version in served.traffic_split:
                del served.traffic_split[version]
                served._wrr_acc = {}
                if self._m_canary is not None:
                    self._m_canary.set(0, model=name, version=str(version))
            if served.shadow is not None \
                    and served.shadow["version"] == version:
                served.shadow = None
            if served.previous_version == version:
                served.previous_version = None
            del served.versions[version]
            served.warmup_state.pop(version, None)
            served.warmup_spec.pop(version, None)
            if served.breakers.pop(version, None) is not None \
                    and self._m_breaker is not None:
                self._m_breaker.set(0, model=name, version=str(version))
            # explicit version numbers in the fallback chain die with the
            # version (resolution would skip them anyway; keeping them
            # would advertise a fallback that can never serve)
            served.fallbacks = [f for f in served.fallbacks
                                if f == "previous" or f != version]

    # ------------------------------------------- resilience: breaker/fallback
    def set_fallback(self, name: str, chain: Sequence[object]) -> None:
        """Designate the failover chain for ``name``: an ordered list of
        version numbers and/or the string ``"previous"`` (re-resolved at
        failover time against whatever is then the previous version).
        Resolution skips entries that are missing, not warm, or whose own
        breaker is not closed — the first survivor serves."""
        with self._lock:
            served = self._get(name)
            parsed: List[object] = []
            for entry in chain:
                if entry == "previous":
                    parsed.append("previous")
                    continue
                v = int(entry)
                if v not in served.versions:
                    raise ModelNotFound(f"{name} has no version {v}")
                parsed.append(v)
            served.fallbacks = parsed

    def get_fallback(self, name: str) -> List[object]:
        with self._lock:
            return list(self._get(name).fallbacks)

    def _resolve_fallback_locked(self, served: ServedModel,
                                 exclude: Optional[int] = None
                                 ) -> Optional[int]:
        """First chain entry that can actually serve. Called under the
        registry lock."""
        for entry in served.fallbacks:
            v = served.previous_version if entry == "previous" else entry
            if v is None or v == exclude or v not in served.versions:
                continue
            state = served.warmup_state.get(v)
            status = None if state is None else state["status"]
            if status not in ("warm", "skipped"):
                continue  # a cold fallback is no fallback
            br = served.breakers.get(v)
            if br is not None and br.state != _breaker.CLOSED:
                continue  # it is quarantined too
            return v
        return None

    def resolve_fallback(self, name: str,
                         exclude: Optional[int] = None) -> Optional[int]:
        """Public resolution (``exclude`` defaults to nothing): the
        version a degraded/brownout request would be served on, or None."""
        with self._lock:
            return self._resolve_fallback_locked(self._get(name), exclude)

    def note_degraded(self, name: str, reason: str) -> None:
        """Count a request served degraded for ``reason`` (the HTTP
        front-end's brownout rerouting reports through here so every
        degraded request lands in ONE series)."""
        if self._m_degraded is not None:
            self._m_degraded.inc(model=name, reason=reason)

    def breaker_state(self, name: str,
                      version: Optional[int] = None) -> Optional[str]:
        """``closed`` / ``open`` / ``half_open`` for ``version`` (default:
        live), or None when breakers are disabled."""
        with self._lock:
            served = self._get(name)
            v = served.current_version if version is None else version
            br = served.breakers.get(v)
            return None if br is None else br.state

    def breaker_states(self, name: str) -> Dict[int, str]:
        """Every version's breaker state (empty when disabled)."""
        with self._lock:
            return {v: br.state
                    for v, br in self._get(name).breakers.items()}

    def _breaker_of(self, served: ServedModel,
                    version: Optional[int]
                    ) -> Optional["_breaker.CircuitBreaker"]:
        if version is None:
            return None
        return served.breakers.get(version)

    def _note_breaker(self, served: ServedModel, version: int,
                      br: "_breaker.CircuitBreaker") -> None:
        if self._m_breaker is not None:
            self._m_breaker.set(br.code, model=served.name,
                                version=str(version))

    def _serve_degraded(self, served: ServedModel, x, deadline_s,
                        exclude: Optional[int], reason: str,
                        original: Optional[BaseException] = None):
        """Serve one request on the fallback chain (synchronous pinned
        path — the dispatcher belongs to the version we are escaping).
        Raises ``original`` (or :class:`VersionQuarantined`) when the
        chain resolves to nothing."""
        with self._lock:
            fb = self._resolve_fallback_locked(served, exclude=exclude)
            model = served.versions[fb].model if fb is not None else None
        if fb is None:
            if original is not None:
                raise original
            br = served.breakers.get(exclude) if exclude is not None \
                else None
            raise VersionQuarantined(
                f"{served.name} v{exclude} is quarantined (circuit "
                f"breaker open) and the fallback chain resolved to "
                f"nothing servable",
                retry_after_s=br.retry_after_s() if br is not None
                else None)
        t0 = time.perf_counter()
        out = np.asarray(model.output(np.asarray(x)))
        if deadline_s is not None \
                and time.perf_counter() - t0 > deadline_s:
            raise InferenceDeadlineExceeded(
                f"degraded predict on {served.name} v{fb} took "
                f"{time.perf_counter() - t0:.3f}s "
                f"(deadline {deadline_s:.3f}s)")
        if self._m_degraded is not None:
            self._m_degraded.inc(model=served.name, reason=reason)
        return out, fb

    # ------------------------------------------------------ canary routing
    def _require_warm(self, served: ServedModel, version: int,
                      what: str) -> None:
        """A version may only receive (or shadow) traffic once its AOT
        bucket warmup finished — 'skipped' counts (no spec / warmup off),
        'pending'/'warming'/'error' do not."""
        state = served.warmup_state.get(version)
        status = None if state is None else state["status"]
        if status not in ("warm", "skipped"):
            raise ValueError(
                f"{served.name} v{version} is not warmed "
                f"(warmup status: {status}); a cold version must never "
                f"receive {what} — rewarm() it first")

    def set_traffic_split(self, name: str,
                          fractions: Dict[int, float]) -> None:
        """Route ``fractions`` of un-pinned predict traffic to non-live
        versions (the live version serves the remainder).  Every target
        must exist, be warm, and not be the live version; fractions are
        in (0, 1] and sum to at most 1.  Deterministic smooth-WRR
        routing; accumulators reset on every split change."""
        with self._lock:
            served = self._get(name)
            total = 0.0
            for v, f in fractions.items():
                if v not in served.versions:
                    raise ModelNotFound(f"{name} has no version {v}")
                if v == served.current_version:
                    raise ValueError(
                        f"{name} v{v} is the live version; split "
                        "fractions name canary versions only")
                f = float(f)
                if not 0.0 < f <= 1.0:
                    raise ValueError(
                        f"fraction for v{v} must be in (0, 1], got {f}")
                self._require_warm(served, v, "a traffic fraction")
                total += f
            if total > 1.0 + 1e-9:
                raise ValueError(
                    f"split fractions sum to {total:.4g} (> 1.0)")
            previous = set(served.traffic_split)
            served.traffic_split = {int(v): float(f)
                                    for v, f in fractions.items()}
            served._wrr_acc = {}
            if self._m_canary is not None:
                for v in previous - set(served.traffic_split):
                    self._m_canary.set(0, model=name, version=str(v))
                for v, f in served.traffic_split.items():
                    self._m_canary.set(f, model=name, version=str(v))

    def clear_traffic_split(self, name: str) -> None:
        """End the canary split: all un-pinned traffic returns to the
        live version's batching dispatcher."""
        self.set_traffic_split(name, {})

    def get_traffic_split(self, name: str) -> Dict[int, float]:
        with self._lock:
            return dict(self._get(name).traffic_split)

    # -------------------------------------------------------- shadow mode
    def set_shadow(self, name: str, version: int, *, sample: float = 1.0,
                   divergence_threshold: float = 1e-3,
                   max_log: int = 100, max_queue: int = 64) -> None:
        """Duplicate every Nth live request (N = round(1/``sample``)) to
        ``version`` off the response path and diff the outputs.  The
        candidate must be warm (it runs a real forward).  Divergences
        past ``divergence_threshold`` (max-abs difference) increment
        ``shadow_divergence_total{model}`` and land in a bounded log."""
        if not 0.0 < float(sample) <= 1.0:
            raise ValueError(f"sample must be in (0, 1], got {sample}")
        with self._lock:
            served = self._get(name)
            if version not in served.versions:
                raise ModelNotFound(f"{name} has no version {version}")
            if version == served.current_version:
                raise ValueError(
                    f"{name} v{version} is the live version; shadow "
                    "mode mirrors traffic to a NON-live candidate")
            self._require_warm(served, version, "shadow traffic")
            served.shadow = {
                "version": int(version), "sample": float(sample),
                "every": max(1, int(round(1.0 / float(sample)))),
                "threshold": float(divergence_threshold),
                "counter": 0, "requests": 0, "divergences": 0,
                "dropped": 0, "max_queue": int(max_queue),
                "log": deque(maxlen=int(max_log)),
            }
        self._ensure_shadow_worker()

    def clear_shadow(self, name: str) -> None:
        with self._lock:
            self._get(name).shadow = None

    def shadow_state(self, name: str) -> Optional[dict]:
        """Counters of the live shadow experiment (None when off)."""
        with self._lock:
            s = self._get(name).shadow
            if s is None:
                return None
            return {k: s[k] for k in ("version", "sample", "requests",
                                      "divergences", "dropped")}

    def shadow_log(self, name: str) -> List[dict]:
        """The bounded divergence log, worst-offenders-keep-rolling."""
        with self._lock:
            s = self._get(name).shadow
            return [] if s is None else list(s["log"])

    def _ensure_shadow_worker(self) -> None:
        with self._shadow_cv:
            if (self._shadow_thread is not None
                    and self._shadow_thread.is_alive()):
                return
            self._shadow_stop = False
            self._shadow_thread = threading.Thread(
                target=self._shadow_loop, name="shadow-worker", daemon=True)
            self._shadow_thread.start()

    def _enqueue_shadow(self, served: ServedModel, x, live_out) -> None:
        """Called under the registry lock from the predict path: count the
        request against the sampling stride and, when it samples, hand
        (input, live output) to the worker — NEVER the model call itself;
        the response path pays a deque append at most."""
        s = served.shadow
        s["counter"] += 1
        if s["counter"] % s["every"]:
            return
        with self._shadow_cv:
            # the bound is per EXPERIMENT: one model's backlog must not
            # silently starve another model's shadow counters
            pending = sum(1 for item in self._shadow_queue
                          if item[0] is served)
            if pending >= s["max_queue"]:
                s["dropped"] += 1
                return
            self._shadow_queue.append(
                (served, s["version"], np.asarray(x),
                 np.asarray(live_out)))
            self._shadow_cv.notify()

    def _shadow_loop(self) -> None:
        while True:
            with self._shadow_cv:
                while not self._shadow_queue:
                    self._shadow_inflight = 0
                    self._shadow_cv.notify_all()  # drain_shadow waiters
                    if self._shadow_stop:
                        return  # shutdown: don't pin the registry forever
                    self._shadow_cv.wait()
                served, version, x, live_out = self._shadow_queue.popleft()
                self._shadow_inflight = 1
            try:
                self._shadow_compare(served, version, x, live_out)
            except Exception:  # noqa: BLE001 — the worker must survive
                pass

    def _shadow_compare(self, served: ServedModel, version: int,
                        x, live_out) -> None:
        with self._lock:
            s = served.shadow
            if s is None or s["version"] != version:
                return  # experiment ended while queued
            model = served.versions[version].model
        try:
            shadow_out = np.asarray(model.output(x))
            diff = float(np.max(np.abs(
                shadow_out.astype(np.float64)
                - np.asarray(live_out).astype(np.float64))))
            error = None
        except Exception as e:  # noqa: BLE001 — a crashing candidate is
            # maximally divergent, not a worker fault
            diff, error = float("inf"), f"{type(e).__name__}: {e}"
        with self._lock:
            s = served.shadow
            if s is None or s["version"] != version:
                return
            s["requests"] += 1
            if self._m_shadow_req is not None:
                self._m_shadow_req.inc(model=served.name)
            if diff > s["threshold"]:
                s["divergences"] += 1
                if self._m_shadow_div is not None:
                    self._m_shadow_div.inc(model=served.name)
                entry = {"diff": diff, "rows": int(np.asarray(x).shape[0]),
                         "ts": time.time()}
                if error is not None:
                    entry["error"] = error
                s["log"].append(entry)

    def drain_shadow(self, timeout_s: float = 5.0) -> bool:
        """Block until the shadow queue is empty and idle (tests and
        deterministic canary ticks); True when drained."""
        deadline = time.monotonic() + timeout_s
        with self._shadow_cv:
            while self._shadow_queue or self._shadow_inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._shadow_cv.wait(remaining)
        return True

    def activate(self, name: str, version: int, *,
                 _kind: str = "activate") -> None:
        """Atomic hot-swap of the live version (rollback's forward twin).
        Activations are serialized by ``_swap_lock`` so the dispatcher's
        live model can never disagree with ``current_version`` when two
        publishers race."""
        with self._swap_lock:
            with self._lock:
                served = self._get(name)
                if version not in served.versions:
                    raise ModelNotFound(f"{name} has no version {version}")
                if version == served.current_version:
                    return
                self._swapping += 1
            try:
                # the swap itself is atomic inside ParallelInference; the
                # _swapping counter only widens the readiness signal around it
                incoming = served.versions[version]
                vmesh = incoming.mesh if incoming.mesh is not None \
                    else self._pi_kw.get("mesh")
                if vmesh is not served.inference.mesh:
                    # batches must land on the incoming version's device
                    # set; swapped-out-of-order requests in flight finish
                    # on the OLD model, which still holds its own placement
                    served.inference.set_mesh(vmesh)
                served.inference.update_model(incoming.model)
                with self._lock:
                    served.previous_version = served.current_version
                    served.current_version = version
                    # a swap invalidates any canary experiment against the
                    # OLD live version: clear the split + shadow so no
                    # stale fraction keeps routing (promote's forward twin)
                    if served.traffic_split and self._m_canary is not None:
                        for v in served.traffic_split:
                            self._m_canary.set(0, model=name,
                                               version=str(v))
                    served.traffic_split = {}
                    served._wrr_acc = {}
                    served.shadow = None
                    self._note_swap(name, version, _kind)
                    # hot-swap keeps warm: the incoming version was warmed
                    # at ITS registration, so the gauge usually stays full
                    self._update_warm_gauge(served)
            finally:
                with self._lock:
                    self._swapping -= 1

    def rollback(self, name: str) -> int:
        """Re-activate the previously live version; returns it. Counts as
        ONE swap event (kind=rollback) — summing the swap counter over
        kinds must equal the number of swaps."""
        with self._lock:
            served = self._get(name)
            prev = served.previous_version
            if prev is None:
                raise ModelNotFound(f"{name} has no previous version")
        self.activate(name, prev, _kind="rollback")
        return prev

    def _note_swap(self, name: str, version: int, kind: str) -> None:
        if self._m_swaps is not None:
            self._m_swaps.inc(model=name, kind=kind)
        if self._m_version is not None:
            self._m_version.set(version, model=name)

    # ------------------------------------------------------------- queries
    def _get(self, name: str) -> ServedModel:
        served = self._models.get(name)
        if served is None:
            raise ModelNotFound(f"no model named {name!r}")
        return served

    def get(self, name: str) -> ServedModel:
        with self._lock:
            return self._get(name)

    def set_cost_ledger(self, ledger) -> None:
        """Attach (or swap) the cost ledger for every present AND future
        dispatcher — the ModelServer wires its own ledger through here
        when the registry was built without one."""
        with self._lock:
            self.cost = ledger
            self._pi_kw["cost"] = ledger
            for served in self._models.values():
                served.inference.cost = ledger

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._models

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def list_models(self) -> List[dict]:
        with self._lock:
            return [self._models[n].describe() for n in sorted(self._models)]

    @property
    def swapping(self) -> bool:
        with self._lock:
            return self._swapping > 0

    def healthy(self) -> bool:
        """Every dispatcher alive (readiness ingredient)."""
        with self._lock:
            return all(m.inference.healthy for m in self._models.values())

    # ------------------------------------------------------------ data path
    def predict(self, name: str, x, *, version: Optional[int] = None,
                deadline_s: Optional[float] = None):
        """Predict through the live dispatcher; see ``predict_versioned``."""
        return self.predict_versioned(name, x, version=version,
                                      deadline_s=deadline_s)[0]

    def predict_versioned(self, name: str, x, *,
                          version: Optional[int] = None,
                          deadline_s: Optional[float] = None):
        """Predict; returns ``(outputs, version_served)``.

        A pinned ``version`` that is not the live one runs synchronously on
        that version's model (no batching) — the escape hatch for canarying
        an old/new version side by side; the live version always goes
        through the coalescing dispatcher. ``version_served`` is attributed
        from the model object that ACTUALLY served the batch, so a hot-swap
        landing mid-request can never mislabel an old model's output with
        the new version number.

        Un-pinned requests honor the canary split: a live
        ``set_traffic_split`` routes each request deterministically
        (smooth WRR) to the live dispatcher or a canary version's model;
        live-path responses additionally feed the shadow sampler when a
        shadow experiment is armed.

        Resilience (un-pinned, dispatcher-bound requests only): the live
        version's circuit breaker is consulted before dispatch — open
        means the request serves on the fallback chain (or raises
        :class:`VersionQuarantined` when the chain is empty); half-open
        admits one probe at a time. A ``DispatcherCrashed`` whose request
        actually reached the forward feeds the breaker, and the request
        itself is re-served on the fallback chain when one exists — the
        crash stays invisible to the client.
        """
        served = self.get(name)
        routed = None
        unpinned = version is None
        with self._lock:
            current = served.current_version
            if version is not None and version not in served.versions:
                raise ModelNotFound(f"{name} has no version {version}")
            if version is None and served.traffic_split:
                routed = served.pick_weighted()
                if routed != current:
                    version = routed
            pinned = (served.versions[version].model
                      if version is not None and version != current else None)
            brk = (self._breaker_of(served, current)
                   if unpinned and pinned is None else None)
        if pinned is not None:
            # the pinned/canary path runs synchronously (no batching) —
            # honor the deadline contract the dispatcher gives live
            # traffic: a response that took longer than its budget is a
            # 504, never an arbitrarily-late 200. (The forward itself is
            # not preemptible, so the check is after the fact.)
            t0 = time.perf_counter()
            out = np.asarray(pinned.output(np.asarray(x)))
            if deadline_s is not None \
                    and time.perf_counter() - t0 > deadline_s:
                raise InferenceDeadlineExceeded(
                    f"synchronous predict on {name} v{version} took "
                    f"{time.perf_counter() - t0:.3f}s "
                    f"(deadline {deadline_s:.3f}s)")
            return out, version
        route = _breaker.ALLOW if brk is None else brk.allow()
        if brk is not None:
            # allow() may have flipped open -> half_open; keep the gauge
            # truthful at every decision point
            self._note_breaker(served, current, brk)
        if route == _breaker.FALLBACK:
            return self._serve_degraded(served, x, deadline_s,
                                        exclude=current,
                                        reason="breaker_open")
        try:
            out, model = served.inference.output(x, deadline_s=deadline_s,
                                                 return_model=True)
        except DispatcherCrashed as e:
            if brk is not None:
                if getattr(e, "dispatched", False):
                    # the forward of the LIVE version took the thread
                    # down — breaker evidence (probe or regular traffic)
                    brk.record_failure(probe=route == _breaker.PROBE)
                elif route == _breaker.PROBE:
                    # the probe never reached the forward (restart still
                    # pending): no verdict, release the probe slot
                    brk.abort_probe()
                self._note_breaker(served, current, brk)
            if not unpinned:
                raise
            # failover: the crash stays invisible when a chain exists
            return self._serve_degraded(served, x, deadline_s,
                                        exclude=current,
                                        reason="crash_failover",
                                        original=e)
        except BaseException:
            if brk is not None and route == _breaker.PROBE:
                brk.abort_probe()  # 504/model error is not a crash verdict
            raise
        if brk is not None:
            brk.record_success(probe=route == _breaker.PROBE)
            self._note_breaker(served, current, brk)
        with self._lock:
            ver = next((mv.version for mv in served.versions.values()
                        if mv.model is model), served.current_version)
            if served.shadow is not None and ver == served.current_version:
                self._enqueue_shadow(served, x, out)
        return out, ver

    # ----------------------------------------------------------- lifecycle
    def shutdown(self) -> None:
        """Stop every dispatcher (flushes in-flight batches first) and
        the shadow worker (a parked daemon thread would otherwise keep
        the registry and every model graph alive for process lifetime)."""
        with self._lock:
            models = list(self._models.values())
        for m in models:
            m.inference.shutdown()
        with self._shadow_cv:
            self._shadow_stop = True
            self._shadow_queue.clear()
            self._shadow_cv.notify_all()
