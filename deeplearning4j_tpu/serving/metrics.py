"""DEPRECATED — the metrics core moved to ``deeplearning4j_tpu.observe.metrics``.

This module re-exports the full surface for backward compatibility; new
code should import from ``deeplearning4j_tpu.observe.metrics`` (or the
``deeplearning4j_tpu.observe`` package), where the shared registry now
serves training, serving, clustering and UI alike.
"""

import warnings as _warnings

from deeplearning4j_tpu.observe.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    HTTPObserverMixin,
    MetricsRegistry,
    default_registry,
    instrument_http,
    parse_prometheus_text,
)

_warnings.warn(
    "deeplearning4j_tpu.serving.metrics moved to "
    "deeplearning4j_tpu.observe.metrics; this alias will be removed",
    DeprecationWarning, stacklevel=2)
