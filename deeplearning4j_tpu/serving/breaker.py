"""Per-version circuit breaker — quarantine for a crashing forward.

A model version whose forward keeps crashing the batching dispatcher must
not be allowed to take its siblings down with it: every crash costs a
dispatcher restart (shared by ALL versions of the name), so a crash-looping
canary would burn the restart budget that its healthy predecessor needs.
The breaker is the standard three-state machine, one instance per
registered version:

- **closed** — traffic flows to the version normally. Forward crashes
  (``DispatcherCrashed`` with ``dispatched=True`` — the request was in the
  dying batch) are counted in a rolling window; reaching
  ``failure_threshold`` crashes within ``window_s`` trips the breaker.
- **open** — the version is quarantined: no request reaches its forward.
  The registry fails un-pinned traffic over to the fallback chain
  (``ModelRegistry.set_fallback``) while the breaker cools down for
  ``cooldown_s``.
- **half-open** — after the cooldown, exactly ONE probe request at a time
  is allowed through to the real forward; ``half_open_probes`` consecutive
  probe successes close the breaker, any probe failure re-opens it for
  another cooldown. Non-probe traffic keeps failing over the whole time,
  so a still-broken version costs at most one request per cooldown.

Time comes from an injectable ``parallel.time_source.TimeSource``
(``ManualTimeSource`` in tests — every transition is exercised without
sleeping). State is exported as ``serving_breaker_state{model,version}``
(0 closed, 1 open, 2 half-open) by the registry, and every transition is
kept in a bounded in-memory log (and structured-logged when a log hub is
active).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from deeplearning4j_tpu.observe import log as _slog

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
#: Prometheus encoding of the state (the ``serving_breaker_state`` gauge)
STATE_CODES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

#: routing verdicts handed to the registry
ALLOW, PROBE, FALLBACK = "allow", "probe", "fallback"


class CircuitBreaker:
    """One version's breaker. Thread-safe; all waits are on the injected
    clock (no sleeps — ``allow()`` only *reads* time)."""

    def __init__(self, *, failure_threshold: int = 3, window_s: float = 30.0,
                 cooldown_s: float = 5.0, half_open_probes: int = 1,
                 time_source=None, name: str = "", max_transitions: int = 64):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if half_open_probes < 1:
            raise ValueError("half_open_probes must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.half_open_probes = int(half_open_probes)
        self._time_source = time_source
        self.name = name  # "model:version", for logs
        self.state = CLOSED
        self.opened_total = 0  # trips, including half-open re-opens
        self._failures: "deque[float]" = deque()
        self._open_until = 0.0
        self._probe_inflight = False
        self._probe_successes = 0
        self.transitions: "deque[dict]" = deque(maxlen=int(max_transitions))
        self._lock = threading.Lock()
        self._log = _slog.get_logger("serving.breaker")

    # ---------------------------------------------------------------- clock
    def _now(self) -> float:
        if self._time_source is not None:
            return self._time_source.current_time_millis() / 1e3
        return time.monotonic()

    # ------------------------------------------------------------- routing
    def allow(self) -> str:
        """Routing verdict for one request: ``"allow"`` (closed — primary
        path), ``"probe"`` (this request IS the half-open probe; report
        its outcome via ``record_success``/``record_failure``/
        ``abort_probe``) or ``"fallback"`` (quarantined)."""
        with self._lock:
            if self.state == CLOSED:
                return ALLOW
            now = self._now()
            if self.state == OPEN:
                if now < self._open_until:
                    return FALLBACK
                self._transition(HALF_OPEN, "cooldown elapsed", now)
                self._probe_successes = 0
                self._probe_inflight = True
                return PROBE
            # half-open: one probe in flight at a time
            if not self._probe_inflight:
                self._probe_inflight = True
                return PROBE
            return FALLBACK

    # ------------------------------------------------------------ verdicts
    def record_success(self, probe: bool = False) -> None:
        with self._lock:
            if self.state == CLOSED:
                # deliberately NOT clearing the failure window: every
                # crash burns a shared dispatcher restart, so a version
                # crashing on 1-in-N requests (poison input) must still
                # trip once the window accumulates the threshold —
                # interleaved successes age failures out only via time
                return
            if self.state == HALF_OPEN and probe:
                self._probe_inflight = False
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    self._transition(
                        CLOSED,
                        f"{self._probe_successes} probe success(es)",
                        self._now())
                    self._failures.clear()

    def record_failure(self, probe: bool = False) -> None:
        """A real forward crash of this version (the caller filters:
        only ``dispatched`` crashes count — a fast-fail while the
        dispatcher restarts never saw the forward)."""
        with self._lock:
            now = self._now()
            if self.state == HALF_OPEN:
                if probe:
                    self._probe_inflight = False
                self._open(now, "probe failed")
                return
            if self.state == OPEN:
                return  # quarantined already; nothing new learned
            self._failures.append(now)
            while self._failures and \
                    now - self._failures[0] > self.window_s:
                self._failures.popleft()
            if len(self._failures) >= self.failure_threshold:
                self._open(now,
                           f"{len(self._failures)} forward crash(es) "
                           f"within {self.window_s:g}s")

    def abort_probe(self) -> None:
        """The probe never reached the forward (dispatcher restart still
        pending) — release the probe slot without a verdict, so the next
        request can try again."""
        with self._lock:
            if self.state == HALF_OPEN:
                self._probe_inflight = False

    # ------------------------------------------------------------ internals
    def _open(self, now: float, reason: str) -> None:
        self._transition(OPEN, reason, now)
        self._open_until = now + self.cooldown_s
        self.opened_total += 1
        self._failures.clear()

    def _transition(self, to: str, reason: str, now: float) -> None:
        self.transitions.append(
            {"at": now, "from": self.state, "to": to, "reason": reason})
        if _slog.get_active_hub() is not None:
            self._log.warning(
                f"circuit breaker {self.name or 'unnamed'}: "
                f"{self.state} -> {to} ({reason})",
                breaker=self.name, from_state=self.state, to_state=to,
                reason=reason)
        self.state = to

    # -------------------------------------------------------------- queries
    @property
    def code(self) -> int:
        return STATE_CODES[self.state]

    def retry_after_s(self) -> Optional[float]:
        """Seconds until the quarantine could lift (None unless open) —
        the ``Retry-After`` hint when no fallback exists."""
        with self._lock:
            if self.state != OPEN:
                return None
            return max(0.0, self._open_until - self._now())

    def describe(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "failures_in_window": len(self._failures),
                    "failure_threshold": self.failure_threshold,
                    "opened_total": self.opened_total,
                    "transitions": list(self.transitions)}
