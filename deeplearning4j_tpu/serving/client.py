"""Serving client — typed access to the ModelServer HTTP surface.

JSON or the ``streaming/codec.py`` binary frame on the predict path (binary
skips float→text→float for large tensors), plus listing, health probes and
a ``/metrics`` scrape that parses back into numbers. Raises ``ServingError``
carrying the HTTP status and the server's ``Retry-After`` hint so callers
can implement backoff.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from deeplearning4j_tpu.serving.metrics import parse_prometheus_text
from deeplearning4j_tpu.serving.server import BINARY_CONTENT_TYPE
from deeplearning4j_tpu.streaming.codec import (deserialize_array,
                                                serialize_array)


class ServingError(RuntimeError):
    """Non-2xx response; carries ``status``, ``message``, ``retry_after_s``."""

    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s


class ModelServingClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    # -------------------------------------------------------------- plumbing
    def _request(self, path: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None):
        req = urllib.request.Request(self.url + path, data=data,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                message = json.loads(body.decode()).get("error", "")
            except Exception:  # noqa: BLE001 - body may not be JSON
                message = body.decode(errors="replace")
            retry = e.headers.get("Retry-After")
            raise ServingError(
                e.code, message,
                float(retry) if retry is not None else None) from None

    # -------------------------------------------------------------- predict
    def predict(self, model: str, inputs, *, version: Optional[int] = None,
                binary: bool = False,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        ref = model if version is None else f"{model}:{version}"
        path = f"/v1/models/{ref}/predict"
        headers = {}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        if binary:
            headers["Content-Type"] = BINARY_CONTENT_TYPE
            _, body, _ = self._request(
                path, serialize_array(np.asarray(inputs)), headers)
            return deserialize_array(body)
        headers["Content-Type"] = "application/json"
        payload = {"inputs": np.asarray(inputs).tolist()}
        _, body, _ = self._request(path, json.dumps(payload).encode(),
                                   headers)
        return np.asarray(json.loads(body.decode())["outputs"])

    # ------------------------------------------------------------ inspection
    def models(self) -> list:
        _, body, _ = self._request("/v1/models")
        return json.loads(body.decode())["models"]

    def model(self, name: str) -> dict:
        _, body, _ = self._request(f"/v1/models/{name}")
        return json.loads(body.decode())

    def healthy(self) -> bool:
        try:
            status, _, _ = self._request("/healthz")
            return status == 200
        except (ServingError, OSError):
            return False

    def ready(self) -> bool:
        try:
            status, _, _ = self._request("/readyz")
            return status == 200
        except ServingError:
            return False
        except OSError:
            return False

    # --------------------------------------------------------------- metrics
    def metrics_text(self) -> str:
        _, body, _ = self._request("/metrics")
        return body.decode()

    def metrics(self) -> dict:
        """Scrape and parse: ``{series: {sorted label pairs: value}}``."""
        return parse_prometheus_text(self.metrics_text())
