"""Serving client — typed access to the ModelServer HTTP surface.

JSON or the ``streaming/codec.py`` binary frame on the predict path (binary
skips float→text→float for large tensors), plus listing, health probes and
a ``/metrics`` scrape that parses back into numbers. Raises ``ServingError``
carrying the HTTP status and the server's ``Retry-After`` hint so callers
can implement backoff.

Connections are PERSISTENT (HTTP/1.1 keep-alive), one per calling thread:
the TCP+handshake tax is paid once per thread, not once per ``predict`` —
without this, a latency benchmark of the server mostly measures the
client's connection churn. A connection the server dropped (restart,
drain) is re-established transparently, once, before the error surfaces
(counted in ``client_reconnects_total``; when the reconnect attempt also
fails, the ORIGINAL failure rides along as ``__cause__``). ``close()``
releases the sockets.

Resilient policy (round 13, opt-in via ``retry=RetryPolicy(...)``):

- **bounded retries with exponential backoff + deterministic jitter** on
  429/503 (and connection errors) — the jitter is hashed from the request
  path and attempt (the elastic supervisor's no-RNG trick), so a replay
  backs off identically; the server's ``Retry-After`` hint is honored as
  a floor on the computed delay.
- **client-side retry budget** (Google SRE: retries must never amplify an
  overload): each first-attempt request earns ``budget_ratio`` tokens,
  each retry spends one — when the bucket is dry, errors surface
  immediately instead of joining the stampede.
- **hedged requests** (*The Tail at Scale*): with ``hedge_after_s`` set,
  an idempotent predict that has not answered within the hedge window
  fires ONE duplicate and the first response wins. Both run to completion
  server-side (HTTP has no cancel), so hedge only against replicated or
  cheap backends; ``client_hedges_total`` / ``client_hedge_wins_total``
  keep the policy honest.

All of it is observable: pass ``metrics=`` (an ``observe.metrics``
registry) for ``client_retries_total{reason}``, ``client_reconnects_total``
and the hedge counters; ``sleep=`` is injectable so tests drive the
backoff without wall-clock waits.

Tracing: ``predict`` runs inside a ``client_predict`` span when a tracer is
active and ALWAYS ships a W3C ``traceparent`` header for it (creating a
fresh trace when no span is open), so the server's ``http_request`` span —
and everything under it — lands in the same timeline. The trace id the
server echoes back is kept on ``client.last_trace_id`` for correlation.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import queue as _queue
import threading
import time
import weakref
from typing import Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.observe.metrics import parse_prometheus_text
from deeplearning4j_tpu.serving.server import BINARY_CONTENT_TYPE
from deeplearning4j_tpu.streaming.codec import (deserialize_array,
                                                serialize_array)


class ServingError(RuntimeError):
    """Non-2xx response; carries ``status``, ``message``, ``retry_after_s``
    and ``trace_id`` (the server's ``X-Trace-Id`` echo, when present)."""

    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s
        self.trace_id: Optional[str] = None


@dataclasses.dataclass
class RetryPolicy:
    """Client-side resilience policy (see module docstring).

    ``statuses`` are the retryable HTTP codes — 429/503 by default: both
    mean "come back later" and both carry ``Retry-After``. 5xx codes that
    mean "the work itself failed" (500) or "the work ran too long" (504)
    are deliberately NOT retried: re-sending them amplifies load without
    changing the outcome."""

    max_retries: int = 3
    base_s: float = 0.05
    factor: float = 2.0
    max_s: float = 2.0
    jitter: float = 0.1
    statuses: Tuple[int, ...] = (429, 503)
    retry_connection_errors: bool = True
    budget_ratio: float = 0.1     # tokens earned per first-attempt request
    budget_cap: float = 10.0
    budget_initial: float = 3.0
    hedge_after_s: Optional[float] = None

    def delay(self, attempt: int, retry_after_s: Optional[float] = None,
              seed: str = "") -> float:
        """Backoff before retry ``attempt`` (1-based): the elastic
        supervisor's ladder (ONE implementation of the deterministic
        sha256 jitter — ``parallel.elastic.BackoffPolicy``), plus the
        server's ``Retry-After`` as a floor — backing off LESS than the
        server asked for would defeat the hint."""
        from deeplearning4j_tpu.parallel.elastic import BackoffPolicy
        d = BackoffPolicy(base_s=self.base_s, factor=self.factor,
                          max_s=self.max_s,
                          jitter=self.jitter).delay(attempt, seed=seed)
        if retry_after_s is not None:
            d = max(d, retry_after_s)
        return d


class ModelServingClient:
    def __init__(self, url: str, timeout: float = 10.0,
                 keep_alive: bool = True,
                 retry: Optional[RetryPolicy] = None,
                 metrics=None, sleep=time.sleep):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.keep_alive = keep_alive
        self.retry = retry
        self.sleep = sleep
        parsed = urlparse(self.url)
        if parsed.scheme not in ("http", "https", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r}")
        self._https = parsed.scheme == "https"
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or (443 if self._https else 80)
        # a path-routed base URL (http://gw/serving) prefixes every request
        self._base_path = parsed.path.rstrip("/")
        self._local = threading.local()
        # every thread's connection, for close(): thread-local storage is
        # only reachable from its own thread, so track them weakly here
        self._conns: "weakref.WeakSet[http.client.HTTPConnection]" = (
            weakref.WeakSet())
        self._conns_lock = threading.Lock()
        self.last_trace_id: Optional[str] = None  # server's X-Trace-Id echo
        # retry budget: a token bucket shared by every thread of this
        # client — the SRE rule that retries stay a bounded FRACTION of
        # organic traffic, whatever the thread count
        self._budget = retry.budget_initial if retry is not None else 0.0
        self._budget_lock = threading.Lock()
        self._m_retries = self._m_reconnects = None
        self._m_hedges = self._m_hedge_wins = None
        if metrics is not None:
            self._m_retries = metrics.counter(
                "client_retries_total",
                "Predict retries by trigger (HTTP status or 'connection')",
                ("reason",))
            self._m_reconnects = metrics.counter(
                "client_reconnects_total",
                "Keep-alive connections re-established after the server "
                "dropped them")
            self._m_hedges = metrics.counter(
                "client_hedges_total",
                "Duplicate (hedged) predicts fired after the hedge window")
            self._m_hedge_wins = metrics.counter(
                "client_hedge_wins_total",
                "Hedged predicts where the DUPLICATE answered first")

    # -------------------------------------------------------------- plumbing
    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self._https
                   else http.client.HTTPConnection)
            conn = cls(self._host, self._port, timeout=self.timeout)
            self._local.conn = conn
            with self._conns_lock:
                self._conns.add(conn)
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    def close(self) -> None:
        """Close every thread's persistent connection."""
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        self._local = threading.local()

    def _request(self, path: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None):
        method = "GET" if data is None else "POST"
        hdrs = dict(headers or {})
        if not self.keep_alive:
            hdrs["Connection"] = "close"
        # one transparent retry when a REUSED connection turns out to have
        # been closed server-side between requests (idle timeout, restart)
        # — never on a fresh connection and never on a timeout, so a slow
        # predict is not silently re-sent
        first_error: Optional[BaseException] = None
        for attempt in (0, 1):
            conn = self._connection()
            fresh = conn.sock is None
            try:
                conn.request(method, self._base_path + path, body=data,
                             headers=hdrs)
                resp = conn.getresponse()
                body = resp.read()
                break
            except (http.client.RemoteDisconnected, http.client.BadStatusLine,
                    ConnectionResetError, BrokenPipeError) as e:
                self._drop_connection()
                if fresh or attempt:
                    # the retry failed too: keep the ORIGINAL dead-
                    # connection failure on the chain — it names the
                    # socket the server actually dropped
                    if first_error is not None:
                        raise e from first_error
                    raise
                first_error = e
                if self._m_reconnects is not None:
                    self._m_reconnects.inc()
            except (http.client.HTTPException, OSError) as e:
                self._drop_connection()
                if first_error is not None:
                    raise e from first_error
                raise
        # Title-Case the keys: http.client preserves wire casing, and a
        # lowercasing proxy must not cost us Retry-After / X-Trace-Id
        resp_headers = {k.title(): v for k, v in resp.getheaders()}
        if not self.keep_alive or resp.will_close:
            self._drop_connection()
        echoed = resp_headers.get("X-Trace-Id")
        if echoed:
            # error responses echo X-Trace-Id too — correlation matters
            # MOST for failures, so capture it before raising
            self.last_trace_id = echoed
        if resp.status >= 400:
            try:
                message = json.loads(body.decode()).get("error", "")
            except Exception:  # noqa: BLE001 - body may not be JSON
                message = body.decode(errors="replace")
            retry = resp_headers.get("Retry-After")
            err = ServingError(
                resp.status, message,
                float(retry) if retry is not None else None)
            err.trace_id = echoed
            raise err
        return resp.status, body, resp_headers

    # -------------------------------------------------------------- predict
    def predict(self, model: str, inputs, *, version: Optional[int] = None,
                binary: bool = False,
                deadline_ms: Optional[float] = None,
                priority: Optional[int] = None) -> np.ndarray:
        """Predict; with a :class:`RetryPolicy` attached, retryable
        failures (429/503, dropped connections) back off and retry under
        the client's retry budget, and ``hedge_after_s`` arms tail-latency
        hedging. ``priority`` rides the ``X-Priority`` header (0 batch,
        1 standard, 2 interactive — brownout sheds low priorities
        first)."""
        ref = model if version is None else f"{model}:{version}"
        path = f"/v1/models/{ref}/predict"
        headers = {}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        if priority is not None:
            headers["X-Priority"] = str(int(priority))
        if self.retry is None:
            return self._predict_attempt(model, path, inputs, binary,
                                         headers)
        return self._predict_resilient(model, path, inputs, binary, headers)

    def _predict_attempt(self, model: str, path: str, inputs, binary: bool,
                         headers: dict) -> np.ndarray:
        """ONE traced request/response (each retry/hedge gets its own
        span — the timeline shows every attempt, not a blur)."""
        tracer = _trace.get_active_tracer()
        if tracer is None:
            return self._predict_send(path, inputs, binary, dict(headers))[0]
        with tracer.span("client_predict", category="serve",
                         attrs={"model": model, "url": self.url}) as sp:
            # the span's own context crosses the wire; the server parents
            # its http_request span to it
            hdrs = dict(headers)
            hdrs["traceparent"] = sp.context.traceparent()
            out, echoed = self._predict_send(path, inputs, binary, hdrs)
            if echoed:  # THIS response's echo only — a shared client may
                sp.set_attribute("server_trace_id", echoed)  # serve threads
            return out

    # ------------------------------------------------------------ resilience
    def _budget_credit(self, pol: RetryPolicy) -> None:
        with self._budget_lock:
            self._budget = min(pol.budget_cap,
                               self._budget + pol.budget_ratio)

    def _budget_spend(self) -> bool:
        with self._budget_lock:
            if self._budget >= 1.0:
                self._budget -= 1.0
                return True
            return False

    @property
    def retry_budget(self) -> float:
        """Tokens left in the retry bucket (observability/tests)."""
        with self._budget_lock:
            return self._budget

    def _predict_resilient(self, model: str, path: str, inputs,
                           binary: bool, headers: dict) -> np.ndarray:
        pol = self.retry
        self._budget_credit(pol)  # organic traffic funds the bucket
        attempt = 0
        while True:
            try:
                if pol.hedge_after_s is not None:
                    return self._predict_hedged(model, path, inputs,
                                                binary, headers, pol)
                return self._predict_attempt(model, path, inputs, binary,
                                             headers)
            except ServingError as e:
                if e.status not in pol.statuses:
                    raise
                err, reason, retry_after = e, str(e.status), e.retry_after_s
            except (http.client.HTTPException, OSError) as e:
                if not pol.retry_connection_errors:
                    raise
                err, reason, retry_after = e, "connection", None
            attempt += 1
            # the budget gates EVERY retry: when it is dry the error
            # surfaces immediately — a stampede of retrying clients is
            # how an overload becomes an outage
            if attempt > pol.max_retries or not self._budget_spend():
                raise err
            if self._m_retries is not None:
                self._m_retries.inc(reason=reason)
            self.sleep(pol.delay(attempt, retry_after, seed=path))

    def _predict_hedged(self, model: str, path: str, inputs, binary: bool,
                        headers: dict, pol: RetryPolicy) -> np.ndarray:
        """Fire the request; if no answer within ``hedge_after_s``, fire
        ONE duplicate and take whichever answers first. An error BEFORE
        the hedge window surfaces immediately (hedging fights latency,
        not failure — the retry loop owns failures). Hedged attempts run
        on short-lived threads with their own connections (closed on
        exit), so hedging trades the keep-alive win for the tail cut —
        price it accordingly."""
        results: "_queue.Queue" = _queue.Queue()

        def run(is_hedge: bool) -> None:
            try:
                results.put((is_hedge, True, self._predict_attempt(
                    model, path, inputs, binary, headers)))
            except BaseException as e:  # noqa: BLE001 — relayed, not lost
                results.put((is_hedge, False, e))
            finally:
                # each attempt thread dialed its own thread-local
                # connection; the thread dies with this call, so close
                # the socket NOW instead of leaking it until GC
                self._drop_connection()

        threading.Thread(target=run, args=(False,), daemon=True).start()
        hedged = False
        try:
            got = results.get(timeout=pol.hedge_after_s)
        except _queue.Empty:
            hedged = True
            if self._m_hedges is not None:
                self._m_hedges.inc()
            threading.Thread(target=run, args=(True,), daemon=True).start()
            got = results.get()
        is_hedge, ok, payload = got
        if ok:
            if is_hedge and self._m_hedge_wins is not None:
                self._m_hedge_wins.inc()
            return payload
        if hedged:
            # first completion failed but its twin is still running —
            # its answer may yet save the request
            is_hedge2, ok2, payload2 = results.get()
            if ok2:
                if is_hedge2 and self._m_hedge_wins is not None:
                    self._m_hedge_wins.inc()
                return payload2
        raise payload

    def _predict_send(self, path: str, inputs, binary: bool, headers: dict):
        """Returns ``(outputs, x_trace_id_or_None)`` — the echo is threaded
        back per call, never through shared client state."""
        if binary:
            headers["Content-Type"] = BINARY_CONTENT_TYPE
            _, body, resp_headers = self._request(
                path, serialize_array(np.asarray(inputs)), headers)
            return deserialize_array(body), resp_headers.get("X-Trace-Id")
        headers["Content-Type"] = "application/json"
        payload = {"inputs": np.asarray(inputs).tolist()}
        _, body, resp_headers = self._request(
            path, json.dumps(payload).encode(), headers)
        return (np.asarray(json.loads(body.decode())["outputs"]),
                resp_headers.get("X-Trace-Id"))

    # ------------------------------------------------------------ inspection
    def models(self) -> list:
        _, body, _ = self._request("/v1/models")
        return json.loads(body.decode())["models"]

    def model(self, name: str) -> dict:
        _, body, _ = self._request(f"/v1/models/{name}")
        return json.loads(body.decode())

    def healthy(self) -> bool:
        try:
            status, _, _ = self._request("/healthz")
            return status == 200
        except (ServingError, OSError):
            return False

    def ready(self) -> bool:
        try:
            status, _, _ = self._request("/readyz")
            return status == 200
        except ServingError:
            return False
        except OSError:
            return False

    # --------------------------------------------------------------- metrics
    def metrics_text(self) -> str:
        _, body, _ = self._request("/metrics")
        return body.decode()

    def metrics(self) -> dict:
        """Scrape and parse: ``{series: {sorted label pairs: value}}``."""
        return parse_prometheus_text(self.metrics_text())
