"""Serving client — typed access to the ModelServer HTTP surface.

JSON or the ``streaming/codec.py`` binary frame on the predict path (binary
skips float→text→float for large tensors), plus listing, health probes and
a ``/metrics`` scrape that parses back into numbers. Raises ``ServingError``
carrying the HTTP status and the server's ``Retry-After`` hint so callers
can implement backoff.

Tracing: ``predict`` runs inside a ``client_predict`` span when a tracer is
active and ALWAYS ships a W3C ``traceparent`` header for it (creating a
fresh trace when no span is open), so the server's ``http_request`` span —
and everything under it — lands in the same timeline. The trace id the
server echoes back is kept on ``client.last_trace_id`` for correlation.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.observe.metrics import parse_prometheus_text
from deeplearning4j_tpu.serving.server import BINARY_CONTENT_TYPE
from deeplearning4j_tpu.streaming.codec import (deserialize_array,
                                                serialize_array)


class ServingError(RuntimeError):
    """Non-2xx response; carries ``status``, ``message``, ``retry_after_s``
    and ``trace_id`` (the server's ``X-Trace-Id`` echo, when present)."""

    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s
        self.trace_id: Optional[str] = None


class ModelServingClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.last_trace_id: Optional[str] = None  # server's X-Trace-Id echo

    # -------------------------------------------------------------- plumbing
    def _request(self, path: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None):
        req = urllib.request.Request(self.url + path, data=data,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                echoed = resp.headers.get("X-Trace-Id")
                if echoed:
                    self.last_trace_id = echoed
                return resp.status, resp.read(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                message = json.loads(body.decode()).get("error", "")
            except Exception:  # noqa: BLE001 - body may not be JSON
                message = body.decode(errors="replace")
            retry = e.headers.get("Retry-After")
            # error responses echo X-Trace-Id too — correlation matters
            # MOST for failures, so capture it before raising
            echoed = e.headers.get("X-Trace-Id")
            if echoed:
                self.last_trace_id = echoed
            err = ServingError(
                e.code, message,
                float(retry) if retry is not None else None)
            err.trace_id = echoed
            raise err from None

    # -------------------------------------------------------------- predict
    def predict(self, model: str, inputs, *, version: Optional[int] = None,
                binary: bool = False,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        ref = model if version is None else f"{model}:{version}"
        path = f"/v1/models/{ref}/predict"
        headers = {}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        tracer = _trace.get_active_tracer()
        if tracer is None:
            return self._predict_send(path, inputs, binary, headers)[0]
        with tracer.span("client_predict", category="serve",
                         attrs={"model": model, "url": self.url}) as sp:
            # the span's own context crosses the wire; the server parents
            # its http_request span to it
            headers["traceparent"] = sp.context.traceparent()
            out, echoed = self._predict_send(path, inputs, binary, headers)
            if echoed:  # THIS response's echo only — a shared client may
                sp.set_attribute("server_trace_id", echoed)  # serve threads
            return out

    def _predict_send(self, path: str, inputs, binary: bool, headers: dict):
        """Returns ``(outputs, x_trace_id_or_None)`` — the echo is threaded
        back per call, never through shared client state."""
        if binary:
            headers["Content-Type"] = BINARY_CONTENT_TYPE
            _, body, resp_headers = self._request(
                path, serialize_array(np.asarray(inputs)), headers)
            return deserialize_array(body), resp_headers.get("X-Trace-Id")
        headers["Content-Type"] = "application/json"
        payload = {"inputs": np.asarray(inputs).tolist()}
        _, body, resp_headers = self._request(
            path, json.dumps(payload).encode(), headers)
        return (np.asarray(json.loads(body.decode())["outputs"]),
                resp_headers.get("X-Trace-Id"))

    # ------------------------------------------------------------ inspection
    def models(self) -> list:
        _, body, _ = self._request("/v1/models")
        return json.loads(body.decode())["models"]

    def model(self, name: str) -> dict:
        _, body, _ = self._request(f"/v1/models/{name}")
        return json.loads(body.decode())

    def healthy(self) -> bool:
        try:
            status, _, _ = self._request("/healthz")
            return status == 200
        except (ServingError, OSError):
            return False

    def ready(self) -> bool:
        try:
            status, _, _ = self._request("/readyz")
            return status == 200
        except ServingError:
            return False
        except OSError:
            return False

    # --------------------------------------------------------------- metrics
    def metrics_text(self) -> str:
        _, body, _ = self._request("/metrics")
        return body.decode()

    def metrics(self) -> dict:
        """Scrape and parse: ``{series: {sorted label pairs: value}}``."""
        return parse_prometheus_text(self.metrics_text())
