"""Serving client — typed access to the ModelServer HTTP surface.

JSON or the ``streaming/codec.py`` binary frame on the predict path (binary
skips float→text→float for large tensors), plus listing, health probes and
a ``/metrics`` scrape that parses back into numbers. Raises ``ServingError``
carrying the HTTP status and the server's ``Retry-After`` hint so callers
can implement backoff.

Connections are PERSISTENT (HTTP/1.1 keep-alive), one per calling thread:
the TCP+handshake tax is paid once per thread, not once per ``predict`` —
without this, a latency benchmark of the server mostly measures the
client's connection churn. A connection the server dropped (restart,
drain) is re-established transparently, once, before the error surfaces.
``close()`` releases the sockets.

Tracing: ``predict`` runs inside a ``client_predict`` span when a tracer is
active and ALWAYS ships a W3C ``traceparent`` header for it (creating a
fresh trace when no span is open), so the server's ``http_request`` span —
and everything under it — lands in the same timeline. The trace id the
server echoes back is kept on ``client.last_trace_id`` for correlation.
"""

from __future__ import annotations

import http.client
import json
import threading
import weakref
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.observe.metrics import parse_prometheus_text
from deeplearning4j_tpu.serving.server import BINARY_CONTENT_TYPE
from deeplearning4j_tpu.streaming.codec import (deserialize_array,
                                                serialize_array)


class ServingError(RuntimeError):
    """Non-2xx response; carries ``status``, ``message``, ``retry_after_s``
    and ``trace_id`` (the server's ``X-Trace-Id`` echo, when present)."""

    def __init__(self, status: int, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message
        self.retry_after_s = retry_after_s
        self.trace_id: Optional[str] = None


class ModelServingClient:
    def __init__(self, url: str, timeout: float = 10.0,
                 keep_alive: bool = True):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.keep_alive = keep_alive
        parsed = urlparse(self.url)
        if parsed.scheme not in ("http", "https", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r}")
        self._https = parsed.scheme == "https"
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or (443 if self._https else 80)
        # a path-routed base URL (http://gw/serving) prefixes every request
        self._base_path = parsed.path.rstrip("/")
        self._local = threading.local()
        # every thread's connection, for close(): thread-local storage is
        # only reachable from its own thread, so track them weakly here
        self._conns: "weakref.WeakSet[http.client.HTTPConnection]" = (
            weakref.WeakSet())
        self._conns_lock = threading.Lock()
        self.last_trace_id: Optional[str] = None  # server's X-Trace-Id echo

    # -------------------------------------------------------------- plumbing
    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self._https
                   else http.client.HTTPConnection)
            conn = cls(self._host, self._port, timeout=self.timeout)
            self._local.conn = conn
            with self._conns_lock:
                self._conns.add(conn)
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    def close(self) -> None:
        """Close every thread's persistent connection."""
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        self._local = threading.local()

    def _request(self, path: str, data: Optional[bytes] = None,
                 headers: Optional[dict] = None):
        method = "GET" if data is None else "POST"
        hdrs = dict(headers or {})
        if not self.keep_alive:
            hdrs["Connection"] = "close"
        # one transparent retry when a REUSED connection turns out to have
        # been closed server-side between requests (idle timeout, restart)
        # — never on a fresh connection and never on a timeout, so a slow
        # predict is not silently re-sent
        for attempt in (0, 1):
            conn = self._connection()
            fresh = conn.sock is None
            try:
                conn.request(method, self._base_path + path, body=data,
                             headers=hdrs)
                resp = conn.getresponse()
                body = resp.read()
                break
            except (http.client.RemoteDisconnected, http.client.BadStatusLine,
                    ConnectionResetError, BrokenPipeError):
                self._drop_connection()
                if fresh or attempt:
                    raise
            except (http.client.HTTPException, OSError):
                self._drop_connection()
                raise
        # Title-Case the keys: http.client preserves wire casing, and a
        # lowercasing proxy must not cost us Retry-After / X-Trace-Id
        resp_headers = {k.title(): v for k, v in resp.getheaders()}
        if not self.keep_alive or resp.will_close:
            self._drop_connection()
        echoed = resp_headers.get("X-Trace-Id")
        if echoed:
            # error responses echo X-Trace-Id too — correlation matters
            # MOST for failures, so capture it before raising
            self.last_trace_id = echoed
        if resp.status >= 400:
            try:
                message = json.loads(body.decode()).get("error", "")
            except Exception:  # noqa: BLE001 - body may not be JSON
                message = body.decode(errors="replace")
            retry = resp_headers.get("Retry-After")
            err = ServingError(
                resp.status, message,
                float(retry) if retry is not None else None)
            err.trace_id = echoed
            raise err
        return resp.status, body, resp_headers

    # -------------------------------------------------------------- predict
    def predict(self, model: str, inputs, *, version: Optional[int] = None,
                binary: bool = False,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        ref = model if version is None else f"{model}:{version}"
        path = f"/v1/models/{ref}/predict"
        headers = {}
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        tracer = _trace.get_active_tracer()
        if tracer is None:
            return self._predict_send(path, inputs, binary, headers)[0]
        with tracer.span("client_predict", category="serve",
                         attrs={"model": model, "url": self.url}) as sp:
            # the span's own context crosses the wire; the server parents
            # its http_request span to it
            headers["traceparent"] = sp.context.traceparent()
            out, echoed = self._predict_send(path, inputs, binary, headers)
            if echoed:  # THIS response's echo only — a shared client may
                sp.set_attribute("server_trace_id", echoed)  # serve threads
            return out

    def _predict_send(self, path: str, inputs, binary: bool, headers: dict):
        """Returns ``(outputs, x_trace_id_or_None)`` — the echo is threaded
        back per call, never through shared client state."""
        if binary:
            headers["Content-Type"] = BINARY_CONTENT_TYPE
            _, body, resp_headers = self._request(
                path, serialize_array(np.asarray(inputs)), headers)
            return deserialize_array(body), resp_headers.get("X-Trace-Id")
        headers["Content-Type"] = "application/json"
        payload = {"inputs": np.asarray(inputs).tolist()}
        _, body, resp_headers = self._request(
            path, json.dumps(payload).encode(), headers)
        return (np.asarray(json.loads(body.decode())["outputs"]),
                resp_headers.get("X-Trace-Id"))

    # ------------------------------------------------------------ inspection
    def models(self) -> list:
        _, body, _ = self._request("/v1/models")
        return json.loads(body.decode())["models"]

    def model(self, name: str) -> dict:
        _, body, _ = self._request(f"/v1/models/{name}")
        return json.loads(body.decode())

    def healthy(self) -> bool:
        try:
            status, _, _ = self._request("/healthz")
            return status == 200
        except (ServingError, OSError):
            return False

    def ready(self) -> bool:
        try:
            status, _, _ = self._request("/readyz")
            return status == 200
        except ServingError:
            return False
        except OSError:
            return False

    # --------------------------------------------------------------- metrics
    def metrics_text(self) -> str:
        _, body, _ = self._request("/metrics")
        return body.decode()

    def metrics(self) -> dict:
        """Scrape and parse: ``{series: {sorted label pairs: value}}``."""
        return parse_prometheus_text(self.metrics_text())
