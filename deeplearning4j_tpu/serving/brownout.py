"""Brownout degradation: shed the cheapest traffic first, serve the rest
on a cheaper version, recover automatically.

The load-shedding / graceful-degradation pattern (SRE Workbook "Managing
Load"): when the serving tier saturates, failing a uniform random slice of
traffic is the WORST policy — better to (1) shed the lowest-priority
requests outright at the front door and (2) degrade what still serves
(route un-pinned predicts to the registry's fallback chain — typically the
previous or int8-quantized version) until pressure clears. Both halves are
driven by this controller; the :class:`~.server.ModelServer` consults it
once per request (cheap: two counter reads, no locks beyond the
controller's own).

Pressure signals, OR-ed:

- **admission saturation**: in-flight slots at or above ``saturation`` of
  ``max_inflight``;
- **firing alert rules**: any rule named in ``watch_rules`` currently
  firing on the attached ``AlertManager`` — this is how a latency
  burn-rate rule (the SLO machinery from round 8) triggers brownout
  *before* the queue is visibly full.

Hysteresis: pressure must hold for ``enter_after_s`` before the brownout
engages, and must stay clear for ``exit_after_s`` before it lifts —
flapping load cannot flap the policy. Time comes from an injectable
``parallel.time_source.TimeSource`` (tests use ``ManualTimeSource``).

Request priorities ride the ``X-Priority`` header: ``0`` = batch /
best-effort, ``1`` = standard (the default), ``2`` = interactive. While
the brownout is active, requests with priority <= ``shed_below`` are shed
with 429 + ``Retry-After``; everything else serves (degraded when
``degrade=True`` and the registry designates a fallback).

State is exported as ``serving_brownout_active`` (gauge) and every
transition is structured-logged; shed/degraded requests land in
``serving_admission_rejections_total{reason="brownout"}`` and
``serving_degraded_requests_total{model,reason="brownout"}``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Sequence

from deeplearning4j_tpu.observe import log as _slog

#: X-Priority conventions (any int is accepted; these name the contract)
PRIORITY_BATCH, PRIORITY_STANDARD, PRIORITY_INTERACTIVE = 0, 1, 2


class BrownoutController:
    """Saturation/alert-driven degradation state machine."""

    def __init__(self, *, admission=None, alerts=None,
                 watch_rules: Sequence[str] = (),
                 saturation: float = 0.9,
                 enter_after_s: float = 1.0, exit_after_s: float = 5.0,
                 shed_below: int = PRIORITY_BATCH,
                 degrade: bool = True,
                 retry_after_s: float = 0.25,
                 time_source=None, metrics=None,
                 max_transitions: int = 64):
        if not 0.0 < float(saturation) <= 1.0:
            raise ValueError(f"saturation must be in (0, 1], "
                             f"got {saturation}")
        self.admission = admission
        self.alerts = alerts
        self.watch_rules = tuple(watch_rules)
        self.saturation = float(saturation)
        self.enter_after_s = float(enter_after_s)
        self.exit_after_s = float(exit_after_s)
        self.shed_below = int(shed_below)
        self.degrade = bool(degrade)
        self.retry_after_s = float(retry_after_s)
        self._time_source = time_source
        self.active = False
        self._pressure_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._last_reason = ""
        self.transitions: "deque[dict]" = deque(maxlen=int(max_transitions))
        self._lock = threading.Lock()
        self._log = _slog.get_logger("serving.brownout")
        self._m_active = None
        if metrics is not None:
            self._m_active = metrics.gauge(
                "serving_brownout_active",
                "1 while brownout degradation (priority shedding + "
                "fallback routing) is engaged")
            self._m_active.set(0)

    # ---------------------------------------------------------------- clock
    def _now(self) -> float:
        if self._time_source is not None:
            return self._time_source.current_time_millis() / 1e3
        return time.monotonic()

    # ------------------------------------------------------------- pressure
    def _pressure(self) -> Optional[str]:
        """The firing pressure signal's name, or None when clear."""
        if self.admission is not None and self.admission.max_inflight > 0:
            inflight = self.admission.inflight
            if inflight >= self.saturation * self.admission.max_inflight:
                return (f"admission saturation "
                        f"{inflight}/{self.admission.max_inflight}")
        if self.alerts is not None and self.watch_rules:
            firing = set(self.alerts.firing())
            hit = sorted(firing.intersection(self.watch_rules))
            if hit:
                return f"alert rule(s) firing: {', '.join(hit)}"
        return None

    def observe(self) -> bool:
        """Advance the state machine against the current signals; returns
        whether the brownout is active. Called once per request by the
        server (and directly by tests)."""
        reason = self._pressure()
        with self._lock:
            now = self._now()
            if reason is not None:
                self._clear_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                if (not self.active
                        and now - self._pressure_since
                        >= self.enter_after_s):
                    self._transition(True, reason, now)
            else:
                self._pressure_since = None
                if self.active:
                    if self._clear_since is None:
                        self._clear_since = now
                    if now - self._clear_since >= self.exit_after_s:
                        self._transition(
                            False, "pressure clear "
                            f"for {self.exit_after_s:g}s", now)
            return self.active

    def _transition(self, active: bool, reason: str, now: float) -> None:
        self.transitions.append({"at": now, "active": active,
                                 "reason": reason})
        self._last_reason = reason
        if _slog.get_active_hub() is not None:
            self._log.warning(
                f"brownout {'ENGAGED' if active else 'lifted'}: {reason}",
                active=active, reason=reason)
        self.active = active
        if self._m_active is not None:
            self._m_active.set(1 if active else 0)

    # --------------------------------------------------------------- policy
    def should_shed(self, priority: int) -> bool:
        """Shed this request at the door? (Only while active.)"""
        return self.active and priority <= self.shed_below

    def describe(self) -> dict:
        with self._lock:
            return {"active": self.active,
                    "last_reason": self._last_reason,
                    "shed_below": self.shed_below,
                    "degrade": self.degrade,
                    "transitions": list(self.transitions)}
